"""Mesh-is-the-spine tests: the PRODUCTION pipeline under an active mesh.

Parity model: the reference distributes every transform/stat through Spark
(FitStagesUtil.scala:96-119, SanityChecker.scala:265-272). Here the same
workflows run under the fake 8-device CPU mesh and must match the unsharded
single-device results numerically — including row counts that do NOT divide
the mesh (auto-padding with masked/weighted identity rows).
"""

import numpy as np
import pytest

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.dag import DagExecutor, compute_dag
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.models.linear import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.pipeline_data import PipelineData
from transmogrifai_tpu.preparators import SanityChecker
from transmogrifai_tpu.selector import (
    BinaryClassificationModelSelector, DataSplitter,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import Workflow


def _mixed_frame(n, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n).astype(float)
    cat = rng.choice(["a", "b", "c"], n)
    vals = rng.normal(size=n) + 0.6 * y
    vals2 = rng.normal(size=n)
    mask = rng.uniform(size=n) > 0.1
    num = [float(v) if m else None for v, m in zip(vals, mask)]
    return fr.HostFrame.from_dict({
        "num": (ft.Real, num),
        "num2": (ft.Real, vals2.tolist()),
        "cat": (ft.PickList, cat.tolist()),
        "label": (ft.RealNN, y.tolist()),
    })


def _automl(frame):
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    vec = transmogrify(list(feats.values()), min_support=1)
    checked = label.transform_with(SanityChecker(), vec)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, seed=3,
        models_and_parameters=[
            (OpLogisticRegression(max_iter=30),
             [{"reg_param": r} for r in (0.01, 0.05)])],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=3))
    pred = label.transform_with(sel, checked)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred).train())
    scores = model.score(frame)
    probs = np.asarray([v["probability_1"]
                        for v in scores.columns[scores.names()[-1]].values])
    return model, probs


@pytest.fixture(scope="module")
def automl203_mesh():
    """ONE full AutoML train on the 203-row (non-divisible) frame under
    the 8-device mesh, shared by the parity tests below (tier-1 wall:
    the same train used to run once per test)."""
    from transmogrifai_tpu.parallel import make_mesh, use_mesh
    with use_mesh(make_mesh(n_data=8)):
        return _automl(_mixed_frame(203))


def test_full_automl_mesh_parity_divisible(mesh8):
    n = 160  # divides the 8-device data axis: no padding engages
    frame = _mixed_frame(n)
    model_m, probs_m = _automl(frame)
    assert probs_m.shape[0] == n and np.all(np.isfinite(probs_m))
    s = model_m.selector_summary()
    assert s is not None and s.holdout_evaluation


def test_full_automl_mesh_parity_nondivisible(automl203_mesh):
    model_m, probs_m = automl203_mesh
    assert probs_m.shape[0] == 203 and np.all(np.isfinite(probs_m))
    s = model_m.selector_summary()
    assert s is not None and s.holdout_evaluation


def test_full_automl_matches_unsharded(automl203_mesh):
    _, probs_mesh = automl203_mesh
    # rebuild the DAG fresh (UIDs differ, data identical) without a mesh
    from transmogrifai_tpu.parallel import use_mesh
    with use_mesh(None):
        _, probs_single = _automl(_mixed_frame(203))
    err = np.max(np.abs(probs_mesh - probs_single))
    assert err < 5e-3, f"mesh vs unsharded divergence {err}"


def test_sanity_checker_stats_mesh_parity(mesh8):
    """SanityChecker's psum-routed moments equal the single-device values on
    a non-divisible row count (padding contributes monoid identity)."""
    n = 203
    frame = _mixed_frame(n, seed=5)

    def run_checker():
        feats = FeatureBuilder.from_frame(frame, response="label")
        label = feats.pop("label")
        vec = transmogrify(list(feats.values()), min_support=1)
        checked = label.transform_with(SanityChecker(), vec)
        data = PipelineData.from_host(frame)
        _, fitted = DagExecutor().fit_transform(data, compute_dag([checked]))
        return [t for layer in fitted for t in layer
                if type(t).__name__ == "DropIndicesModel"][0].summary

    s_mesh = run_checker()
    from transmogrifai_tpu.parallel.mesh import _current
    token = _current.set(None)
    try:
        s_single = run_checker()
    finally:
        _current.reset(token)

    assert s_mesh.dropped == s_single.dropped
    for cm, cs in zip(s_mesh.column_stats, s_single.column_stats):
        assert cm.mean == pytest.approx(cs.mean, abs=1e-4)
        assert cm.variance == pytest.approx(cs.variance, abs=1e-4)
        assert cm.min == pytest.approx(cs.min, abs=1e-5)
        assert cm.max == pytest.approx(cs.max, abs=1e-5)
        if np.isfinite(cm.corr_label) or np.isfinite(cs.corr_label):
            assert cm.corr_label == pytest.approx(cs.corr_label, abs=1e-4)


def test_mesh4x2_grid_sharded_over_model(mesh4x2):
    """Under a (4 data, 2 model) mesh the 4-point LR grid trains with its
    candidate axis sharded over 'model' and rows padded over 'data'."""
    n = 101  # not divisible by 4
    frame = _mixed_frame(n, seed=9)
    model, probs = _automl(frame)
    assert probs.shape[0] == n and np.all(np.isfinite(probs))


def test_pipeline_data_pads_and_slices(mesh8):
    n = 13  # pads to 16 on an 8-device data axis
    rng = np.random.default_rng(0)
    vals = rng.normal(size=n)
    frame = fr.HostFrame.from_dict({"a": (ft.Real, vals.tolist())})
    data = PipelineData.from_host(frame)
    col = data.device_col("a")
    assert int(col.values.shape[0]) == 16  # padded
    assert data.n_rows == n                # logical
    m = np.asarray(data.row_mask())
    assert m.sum() == n and m[n:].sum() == 0
    back = data.host_col("a")              # pull slices padding off
    assert len(back) == n
    np.testing.assert_allclose(np.asarray(back.values), vals, rtol=1e-6)


def test_spearman_and_feature_corr_drop():
    """Spearman label correlation + maxFeatureCorr transitive drop semantics
    (reference DerivedFeatureFilterUtils.reasonsToRemove: the LATER column
    of a too-correlated pair drops)."""
    n = 400
    rng = np.random.default_rng(1)
    y = rng.integers(0, 2, n).astype(float)
    base = rng.normal(size=n) + 0.8 * y
    dup = base * 2.0 + 1e-3 * rng.normal(size=n)  # ~perfectly corr with base
    # monotone-but-nonlinear relation: strong Spearman, weaker Pearson
    mono = np.exp(base / 2)
    frame = fr.HostFrame.from_dict({
        "base": (ft.Real, base.tolist()),
        "dup": (ft.Real, dup.tolist()),
        "mono": (ft.Real, mono.tolist()),
        "label": (ft.RealNN, y.tolist()),
    })
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    vec = transmogrify(list(feats.values()), min_support=1)
    checked = label.transform_with(
        SanityChecker(correlation_type="spearman",
                      max_feature_correlation=0.95), vec)
    data = PipelineData.from_host(frame)
    _, fitted = DagExecutor().fit_transform(data, compute_dag([checked]))
    model = [t for layer in fitted for t in layer
             if type(t).__name__ == "DropIndicesModel"][0]
    s = model.summary
    assert s.correlation_type == "spearman"
    by_name = {c.name: c for c in s.column_stats}
    base_col = next(c for nm, c in by_name.items() if nm.startswith("base"))
    dup_col = next(c for nm, c in by_name.items() if nm.startswith("dup"))
    mono_col = next(c for nm, c in by_name.items() if nm.startswith("mono"))
    # spearman(mono, label) == spearman(base, label): ranks are identical
    assert mono_col.corr_label == pytest.approx(base_col.corr_label, abs=1e-6)
    # the later of the (base, dup) pair drops on feature-feature corr
    assert not base_col.dropped
    assert dup_col.dropped
    assert any("feature correlation" in r for r in dup_col.reasons)


def test_sampling_cap():
    n = 500
    rng = np.random.default_rng(2)
    y = rng.integers(0, 2, n).astype(float)
    frame = fr.HostFrame.from_dict({
        "a": (ft.Real, (rng.normal(size=n) + y).tolist()),
        "label": (ft.RealNN, y.tolist()),
    })
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    vec = transmogrify(list(feats.values()), min_support=1)
    checked = label.transform_with(
        SanityChecker(sample_upper_limit=200), vec)
    data = PipelineData.from_host(frame)
    _, fitted = DagExecutor().fit_transform(data, compute_dag([checked]))
    model = [t for layer in fitted for t in layer
             if type(t).__name__ == "DropIndicesModel"][0]
    s = model.summary
    assert s.n_rows == 200
    assert s.sample_fraction == pytest.approx(0.4)
    # statistics still sane on the sample
    a_col = next(c for c in s.column_stats if c.name.startswith("a"))
    assert 0.2 < a_col.corr_label < 0.9


def test_tree_histograms_row_sharded_parity(mesh8):
    """Distributed tree fit: with the binned matrix row-sharded over 'data',
    the per-shard scatter histograms all-reduce inside the jitted program
    (XLA's psum insertion — the Rabit all-reduce analog, trees.py docstring)
    and the grown ensemble matches the unsharded fit exactly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from transmogrifai_tpu.models.trees import train_ensemble
    from transmogrifai_tpu.parallel.mesh import DATA_AXIS, current_mesh

    rng = np.random.default_rng(17)
    n, d = 1024, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = ((X[:, 0] * X[:, 1] > 0) ^ (X[:, 2] > 0.5)).astype(np.float64)
    from transmogrifai_tpu.models.trees import bin_data, quantile_bin_edges
    edges = quantile_bin_edges(X, 32)
    Xb = bin_data(jnp.asarray(X), jnp.asarray(edges))
    yj = jnp.asarray(y)
    w = jnp.ones_like(yj)

    kw = dict(n_rounds=10, max_depth=5, n_bins=32, n_out=1, loss="logistic",
              learning_rate=jnp.float32(0.3), reg_lambda=jnp.float32(1.0),
              gamma=jnp.float32(0.0), min_child_weight=jnp.float32(1.0),
              subsample=1.0, colsample=1.0, base_score=jnp.float32(0.0),
              bootstrap=False, seed=7)
    trees_single, _ = train_ensemble(Xb, yj, w, **kw)

    ctx = current_mesh()
    shard = NamedSharding(ctx.mesh, P(DATA_AXIS))
    shard2 = NamedSharding(ctx.mesh, P(DATA_AXIS, None))
    Xb_s = jax.device_put(Xb, shard2)
    y_s = jax.device_put(yj, shard)
    w_s = jax.device_put(w, shard)

    # level-0 histograms: per-shard partials all-reduce to the same totals
    # (up to fp summation order)
    from transmogrifai_tpu.ops.histograms import node_bin_histogram_xla
    node0 = jnp.zeros(n, jnp.int32)
    g = yj.astype(jnp.float32)
    hg1, hh1 = node_bin_histogram_xla(Xb, node0, g, w.astype(jnp.float32),
                                      n_nodes=1, n_bins=32)
    hg2, hh2 = node_bin_histogram_xla(
        Xb_s, jax.device_put(node0, shard), jax.device_put(g, shard),
        jax.device_put(w.astype(jnp.float32), shard), n_nodes=1, n_bins=32)
    np.testing.assert_allclose(np.asarray(hg1), np.asarray(hg2), atol=1e-3)
    np.testing.assert_allclose(np.asarray(hh1), np.asarray(hh2), atol=1e-3)

    # the full sharded ensemble trains and matches the unsharded model's
    # quality (exact tree structure may flip on near-tie gains: the sharded
    # reduction legitimately reorders float summation)
    trees_mesh, _ = train_ensemble(Xb_s, y_s, w_s, **kw)
    from transmogrifai_tpu.models.trees import predict_ensemble
    m_single = predict_ensemble(
        Xb, trees_single, n_out=1, learning_rate=jnp.float32(0.3),
        base_score=jnp.float32(0.0), bootstrap=False)
    m_mesh = predict_ensemble(
        Xb, trees_mesh, n_out=1, learning_rate=jnp.float32(0.3),
        base_score=jnp.float32(0.0), bootstrap=False)
    from transmogrifai_tpu.evaluators import OpBinaryClassificationEvaluator

    def auc(margin):
        import transmogrifai_tpu.frame as frm
        p = jax.nn.sigmoid(margin[:, 0])
        pc = frm.PredictionColumn(
            (p > 0.5).astype(jnp.float32),
            jnp.stack([-margin[:, 0], margin[:, 0]], 1),
            jnp.stack([1 - p, p], 1))
        return OpBinaryClassificationEvaluator().evaluate_arrays(yj, pc).au_roc

    a1, a2 = auc(m_single), auc(m_mesh)
    assert a1 > 0.95 and abs(a1 - a2) < 0.02, (a1, a2)


def test_idf_and_min_variance_mesh_parity(mesh8):
    """OpIDF / MinVarianceFilter weight their reductions by row_mask so
    mesh-padding rows contribute monoid identity (advisor round-2 high):
    unmasked sums would inflate document counts and skew variances toward
    zero-mean on non-divisible row counts."""
    n = 203  # not divisible by 8 -> padded device rows
    rng = np.random.default_rng(11)
    docs = [[t for t in rng.choice(["a", "b", "c", "d"],
                                   rng.integers(0, 4)).tolist()]
            for _ in range(n)]
    frame = fr.HostFrame.from_dict({"toks": (ft.TextList, docs)})

    def run():
        import transmogrifai_tpu.dsl  # noqa: F401
        feats = FeatureBuilder.from_frame(frame)
        f = feats["toks"].tf(num_features=16).idf(min_doc_freq=2)
        filt = f.filter_min_variance(1e-6)
        data = PipelineData.from_host(frame)
        out, fitted = DagExecutor().fit_transform(
            data, compute_dag([f, filt]))
        idf_model = [t for layer in fitted for t in layer
                     if type(t).__name__ == "IDFModel"][0]
        mv_model = [t for layer in fitted for t in layer
                    if type(t).__name__ == "MinVarianceFilterModel"][0]
        return (np.asarray(idf_model.idf), list(mv_model.keep_indices),
                np.asarray(out.host_col(filt.name).values))

    idf_m, keep_m, vals_m = run()
    from transmogrifai_tpu.parallel.mesh import _current
    token = _current.set(None)
    try:
        idf_s, keep_s, vals_s = run()
    finally:
        _current.reset(token)
    assert np.allclose(idf_m, idf_s, atol=1e-5), "IDF skewed by padding rows"
    assert keep_m == keep_s
    assert vals_m.shape == vals_s.shape
    assert np.allclose(vals_m, vals_s, atol=1e-5)


def test_workflow_cv_under_mesh_parity(mesh4x2):
    """The leakage-free workflow-level CV cut (cutDAG: before/during/after
    refit per fold) trained UNDER an active mesh matches the unsharded
    run — the last distributed path the spine tests didn't cover (r4)."""
    import contextlib

    from transmogrifai_tpu.dag import cut_dag
    from transmogrifai_tpu.parallel import use_mesh

    rng = np.random.default_rng(0)
    n = 203  # deliberately not divisible by the data axis
    y = rng.integers(0, 2, n).astype(float)
    frame = fr.HostFrame.from_dict({
        "x1": (ft.Real, (rng.normal(size=n) + 0.8 * y).tolist()),
        "x2": (ft.Real, rng.normal(size=n).tolist()),
        "label": (ft.RealNN, y.tolist())})

    def run(active):
        # the fixture keeps the mesh active for the whole test: the
        # unsharded leg must explicitly clear it, not just skip re-entry
        scope = contextlib.nullcontext() if active else use_mesh(None)
        with scope:
            feats = FeatureBuilder.from_frame(frame, response="label")
            label = feats.pop("label")
            vec = transmogrify(list(feats.values()))
            checked = label.transform_with(SanityChecker(), vec)
            sel = BinaryClassificationModelSelector.with_cross_validation(
                n_folds=2, seed=3, models_and_parameters=[
                    (OpLogisticRegression(max_iter=20),
                     [{"reg_param": 0.05}])])
            pred = label.transform_with(sel, checked)
            # the cut actually engages: the label-dependent SanityChecker
            # must land in the in-CV (per-fold refit) partition — without
            # this, train() silently falls back to the plain fit and this
            # test degrades to trivial mesh parity
            cut = cut_dag([pred])
            assert cut.selector is not None and any(
                type(st).__name__ == "SanityChecker"
                for layer in cut.during for st in layer)
            m = (Workflow().set_input_frame(frame)
                 .set_result_features(pred).with_workflow_cv().train())
            scored = m.score(frame)
            return np.asarray([v["probability_1"] for v in
                               scored.columns[pred.name].values])

    a, b = run(True), run(False)
    assert float(np.abs(a - b).max()) < 5e-5


def test_sorted_engine_sharded_parity(mesh8):
    """Distributed SORTED-engine trees (train_ensemble_sharded): per-shard
    local sort bookkeeping + one histogram psum per level must reproduce
    the unsharded sorted fit — same split structure, same predictions —
    for GBT (margin updates from shard-local row_pred) on the 8-device
    virtual mesh."""
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.models.trees import (
        bin_data, predict_ensemble, quantile_bin_edges, train_ensemble,
        train_ensemble_sharded,
    )
    from transmogrifai_tpu.parallel.mesh import (
        current_mesh, shard_training_rows,
    )

    rng = np.random.default_rng(23)
    n, d = 4096, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = ((X[:, 0] * X[:, 1] > 0) ^ (X[:, 2] > 0.3)).astype(np.float64)
    edges = quantile_bin_edges(X, 32)
    Xb = bin_data(jnp.asarray(X), jnp.asarray(edges))
    yj = jnp.asarray(y)
    w = jnp.ones_like(yj)

    kw = dict(n_rounds=6, max_depth=5, n_bins=32, n_out=1, loss="logistic",
              learning_rate=jnp.float32(0.3), reg_lambda=jnp.float32(1.0),
              gamma=jnp.float32(0.0), min_child_weight=jnp.float32(1.0),
              subsample=1.0, colsample=1.0, base_score=jnp.float32(0.0),
              bootstrap=False, seed=7)
    trees_single, gains_single = train_ensemble(Xb, yj, w, hist="sorted",
                                                **kw)

    ctx = current_mesh()
    Xb_s, y_s, w_s = shard_training_rows(Xb, yj, w)
    trees_mesh, gains_mesh = train_ensemble_sharded(ctx, Xb_s, y_s, w_s,
                                                    **kw)

    m1 = predict_ensemble(Xb, trees_single, n_out=1,
                          learning_rate=jnp.float32(0.3),
                          base_score=jnp.float32(0.0), bootstrap=False)
    m2 = predict_ensemble(Xb, trees_mesh, n_out=1,
                          learning_rate=jnp.float32(0.3),
                          base_score=jnp.float32(0.0), bootstrap=False)
    # identical split decisions up to float-summation-order near-ties:
    # predictions must agree tightly
    np.testing.assert_allclose(np.asarray(m1), np.asarray(m2), atol=5e-3)
    np.testing.assert_allclose(np.asarray(gains_single),
                               np.asarray(gains_mesh), rtol=5e-2, atol=1.0)
