"""Line-rate explainability (round 15): compiled LOCO through the
serving stack — parity vs the offline ``RecordInsightsLOCO`` path,
program-cache bounds (both the serving explain programs and the offline
LOCO program cache), OOM mask-chunk rungs, the HTTP ``explain`` field
with lineage, hot-swap survival, and router passthrough.

ONE module-scoped trained model backs every case (tier-1 wall budget:
this file must stay lean)."""

import json
import time

import numpy as np
import pytest

from transmogrifai_tpu import dsl  # noqa: F401
from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.models.linear import OpLogisticRegression
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.selector import BinaryClassificationModelSelector
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow import Workflow

N = 160


def _train(max_iter: int = 25):
    from transmogrifai_tpu.uid import UID
    UID.reset()  # versions of one endpoint share feature names
    rng = np.random.default_rng(5)
    x1 = rng.normal(size=N)
    x2 = rng.normal(size=N)
    color = rng.choice(["red", "green", "blue"], size=N)
    logit = 1.6 * x1 - x2 + (color == "red") * 1.3
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-logit))).astype(float)
    frame = fr.HostFrame.from_dict({
        "y": (ft.RealNN, y.tolist()),
        "x1": (ft.Real, x1.tolist()),
        "x2": (ft.Real, x2.tolist()),
        "color": (ft.PickList, color.tolist()),
    })
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify([feats["x1"], feats["x2"], feats["color"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=max_iter), [{}])])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    rows = [{"x1": float(x1[i]), "x2": float(x2[i]),
             "color": str(color[i])} for i in range(N)]
    return model, rows, frame


@pytest.fixture(scope="module")
def fitted():
    return _train()


def _pred_stage(model):
    pred_f = model._prediction_feature()
    for t in model.stages():
        if t.get_output() == pred_f:
            return t, t.runtime_input_names()[-1]
    raise AssertionError("no prediction stage")


def _offline_deltas(model, frame, rows_idx, top_k=500):
    from transmogrifai_tpu.insights.loco import RecordInsightsLOCO
    pstage, vec_name = _pred_stage(model)
    col = model.transform(frame).host_col(vec_name)
    vals = RecordInsightsLOCO(model=pstage,
                              top_k=top_k).host_apply(col).values
    return [{k: float(v) for k, v in vals[i].items()} for i in rows_idx]


def test_compiled_explainer_parity_vs_offline_loco(fitted):
    """Served attributions == offline RecordInsightsLOCO deltas (the
    acceptance bound the committed artifact also gates)."""
    from transmogrifai_tpu.serving.explain import CompiledExplainer
    model, rows, frame = fitted
    ex = CompiledExplainer(model, top_k=500, max_batch=16, min_bucket=8)
    docs, exps = ex.explain_batch(rows[:6])
    offline = _offline_deltas(model, frame, range(6))
    assert len(docs) == 6 and len(exps) == 6
    for served, ref in zip(exps, offline):
        assert served, "no attributions served"
        for e in served:
            assert e["name"] in ref
            assert abs(e["delta"] - ref[e["name"]]) <= 1e-5
        # ordering: |delta| non-increasing (offline Abs strategy)
        mags = [abs(e["delta"]) for e in served]
        assert mags == sorted(mags, reverse=True)


def test_explain_program_cache_bounds_and_reuse(fitted):
    """Explain programs are padded-bucket bounded: repeat traffic at any
    admitted size compiles nothing new, and the private program dict
    holds one explain entry per (layer-run, chunk) plus the plain
    layers."""
    from transmogrifai_tpu.serving.explain import CompiledExplainer
    model, rows, _ = fitted
    ex = CompiledExplainer(model, top_k=3, max_batch=16, min_bucket=8)
    ex.warmup(rows[0])
    warm = dict(ex.counters.compiles_by_bucket())
    for n in (1, 3, 8, 11, 16, 2, 16):
        docs, exps = ex.explain_batch(rows[:n])
        assert len(docs) == n and len(exps) == n
    assert dict(ex.counters.compiles_by_bucket()) == warm, \
        "steady-state explained traffic recompiled"


def test_offline_loco_program_cache_reuse(fitted):
    """Satellite regression: repeated ``host_apply`` batches and
    ``transform_row`` calls reuse ONE compiled program per shape instead
    of re-tracing the masked-score closure every invocation."""
    from transmogrifai_tpu.insights.loco import (
        RecordInsightsLOCO, loco_programs,
    )
    model, rows, frame = fitted
    pstage, vec_name = _pred_stage(model)
    col = model.transform(frame).host_col(vec_name)
    X = np.asarray(col.values, np.float32)
    sub = fr.HostColumn(ft.OPVector, X[:32], meta=col.meta)
    loco = RecordInsightsLOCO(model=pstage, top_k=4)
    loco_programs.clear()
    a = loco.host_apply(sub).values
    s1 = loco_programs.stats()
    assert s1["insertions"] == 1
    # same shape again — a pure hit, even from a NEW stage instance
    b = RecordInsightsLOCO(model=pstage, top_k=4).host_apply(sub).values
    s2 = loco_programs.stats()
    assert s2["insertions"] == 1 and s2["hits"] >= 1
    assert list(a[0].items()) == list(b[0].items())
    # transform_row: one [1, d] program shared across rows
    r1 = loco.transform_row(X[0])
    loco.transform_row(X[1])
    loco.transform_row(X[2])
    s3 = loco_programs.stats()
    assert s3["insertions"] == 2  # the single [1, d] entry
    assert s3["hits"] >= s2["hits"] + 2
    assert r1  # non-empty insight map
    # Avg strategy caches separately, keyed on its chunking
    RecordInsightsLOCO(model=pstage, top_k=4,
                       aggregation_strategy="Avg").host_apply(sub)
    assert loco_programs.stats()["insertions"] == 3


def test_explain_oom_rung_halves_mask_chunk(fitted):
    """Resource ladder at site serving.explain: an OOM explain dispatch
    halves the mask-chunk width and re-serves the SAME batch — same
    attributions, request settles, degradation counted."""
    from transmogrifai_tpu.serving.server import ScoringServer
    from transmogrifai_tpu.utils.faults import fault_plan
    from transmogrifai_tpu.utils.resources import resource_counters
    model, rows, _ = fitted
    # default mask_chunk (64) >> group count: the rung must halve the
    # EFFECTIVE chunk (the width programs were traced at), not the raw
    # knob — regression for the no-op-rung keying mismatch
    with ScoringServer(model, max_batch=16, min_bucket=16, explain=True,
                       explain_top_k=4, retries=1) as srv:
        srv.start(warmup_row=rows[0])
        clean = srv.explain(rows[3], timeout_s=60)
        before = resource_counters.degradations_by_site.get(
            "serving.explain", 0)
        n_groups = srv.explainer.n_groups
        assert srv.explainer.effective_mask_chunk() == n_groups
        with fault_plan("oom@serving.explain#0"):
            doc = srv.explain(rows[3], timeout_s=60)
        assert srv.explainer.mask_chunk == n_groups // 2
        assert srv.explainer.effective_mask_chunk() == n_groups // 2
        assert resource_counters.degradations_by_site.get(
            "serving.explain", 0) == before + 1
        assert doc["explanations"], "rung retry lost the attributions"
        got = {e["name"]: e["delta"] for e in doc["explanations"]}
        ref = {e["name"]: e["delta"] for e in clean["explanations"]}
        assert set(got) == set(ref)
        for k, v in got.items():
            assert abs(v - ref[k]) <= 1e-6
        # post-rung traffic keeps serving compiled at the smaller chunk
        assert srv.explain(rows[4], timeout_s=60)["explanations"]
        assert srv.explain_metrics.degraded_batches == 0


def test_fleet_http_explain_field_lineage_and_hot_swap(fitted):
    """The end-to-end surface: POST /score with {"explain": K} returns
    top-K attributions + trace id + lineage; plain requests carry no
    explanations; a mid-run hot-swap keeps explaining with the PROMOTED
    version's lineage; the scrape exposes transmogrifai_explain_*."""
    import http.client

    from transmogrifai_tpu.serving import FleetServer
    model, rows, _ = fitted
    v2_model, _, _ = _train(max_iter=26)
    fleet = FleetServer(max_batch=16, min_bucket=16, shadow_rows=4,
                        metrics_port=0, explain=True, explain_top_k=3)
    fleet.register(model=model, model_id="m")
    fleet.start(warmup_rows={"m": rows[0]})
    try:
        conn = http.client.HTTPConnection("127.0.0.1",
                                          fleet.metrics_http.port,
                                          timeout=30)

        def post(row):
            conn.request("POST", "/score/m", json.dumps(row).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp, json.loads(resp.read())

        resp, plain = post(dict(rows[1]))
        assert resp.status == 200 and "explanations" not in plain
        resp, doc = post({**rows[1], "explain": 2})
        assert resp.status == 200
        assert len(doc["explanations"]) <= 2 and doc["explanations"]
        assert doc["traceId"] and doc["lineage"]["version"] == "v1"
        # keep some live rows flowing so the swap's shadow gate has feed
        for r in rows[2:6]:
            post({**r, "explain": True})
        fleet.hot_swap("m", model=v2_model, tolerance=1.0)
        resp, doc2 = post({**rows[1], "explain": True})
        assert resp.status == 200 and doc2["explanations"]
        assert doc2["lineage"]["version"] == "v2"
        lane = fleet.active_lanes()["m"]
        assert lane.post_warmup_explain_compiles() == {}
        # scrape: the explain series render model-labeled
        conn.request("GET", "/metrics")
        body = conn.getresponse().read().decode()
        assert "transmogrifai_explain_requests_admitted_total" in body
        assert 'model="m"' in body
        assert "transmogrifai_explain_latency_seconds_bucket" in body
        conn.close()
    finally:
        fleet.stop()


def test_router_passes_explain_field_through(fitted):
    """Scale-out passthrough: the router proxies request bodies
    verbatim, so the explain directive reaches the replica unchanged."""
    import http.client

    from transmogrifai_tpu.scaleout.router import Router
    from transmogrifai_tpu.serving.http import MetricsServer
    seen = {}

    def score(mid, row, tid):
        seen.update(row)
        return {"ok": True, "explain_seen": row.get("explain")}

    srv = MetricsServer(render_fn=lambda: "", health_fn=lambda: {},
                        score_fn=score, port=0).start()
    router = Router(port=0).start()
    try:
        router.set_replica("r0", srv.port)
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=10)
        conn.request("POST", "/score/m1",
                     json.dumps({"x": 1.0, "explain": 5}).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["explain_seen"] == 5
        assert seen.get("explain") == 5
        conn.close()
    finally:
        router.stop()
        srv.stop()


def test_explain_snapshot_and_disabled_lane(fitted):
    """Snapshot carries the explain block; submit_explain without the
    lane is a loud ValueError; per-request top-K overrides the lane
    default."""
    from transmogrifai_tpu.serving.server import ScoringServer
    model, rows, _ = fitted
    with ScoringServer(model, max_batch=16, min_bucket=16,
                       explain=True, explain_top_k=2) as srv:
        srv.start(warmup_row=rows[0])
        d_default = srv.explain(rows[2], timeout_s=60)
        d_wide = srv.explain(rows[2], top_k=500, timeout_s=60)
        assert len(d_default["explanations"]) <= 2
        assert len(d_wide["explanations"]) > len(d_default["explanations"])
        snap = srv.snapshot()
        assert snap["explain"]["config"]["topK"] == 2
        assert snap["explain"]["requests"]["completed"] >= 2
        assert snap["explain"]["postWarmupCompiles"] == {}
    with ScoringServer(model, max_batch=16) as srv2:
        with pytest.raises(ValueError):
            srv2.submit_explain(rows[0])
