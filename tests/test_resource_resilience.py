"""Resource-exhaustion resilience (the adaptive degradation ladder):
OOM/ENOSPC classification over shared cause chains, the oom/enospc
fault kinds, the sweep's stacked->fold-loop and tree lane-chunk rungs
(bitwise winner parity + checkpointed rung log), the serving
bucket-shedding rung (zero dropped requests), counted best-effort
ENOSPC handling in durable writes and the event spill, the continuous
retrain window shrink, and the transmogrifai_resource_* / healthz
surfaces — with the ladder-disabled fail-fast contract asserted
alongside every rung."""

import errno
import json
import os
import warnings

import numpy as np
import pytest

from transmogrifai_tpu import dsl  # noqa: F401 — installs operators
from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.models.linear import OpLogisticRegression
from transmogrifai_tpu.models.trees import OpGBTClassifier
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.selector import (
    BinaryClassificationModelSelector, DataSplitter,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.uid import UID
from transmogrifai_tpu.utils import resources
from transmogrifai_tpu.utils.faults import (
    FaultPlan, FaultSpec, XlaRuntimeError, fault_plan,
)
from transmogrifai_tpu.utils.resources import resource_counters
from transmogrifai_tpu.utils.retry import is_transient_device_error
from transmogrifai_tpu.workflow import Workflow


def _oom_error() -> XlaRuntimeError:
    return XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes")


@pytest.fixture(autouse=True)
def _clean_counters():
    resource_counters.reset()
    yield
    resource_counters.reset()


def _frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + 0.8 * y
    return fr.HostFrame.from_dict({
        "x": (ft.Real, x.tolist()),
        "x2": (ft.Real, rng.normal(size=n).tolist()),
        "label": (ft.RealNN, y.tolist()),
    })


def _train(selector, frame):
    UID.reset()
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    vec = transmogrify(list(feats.values()), min_support=1)
    pred = label.transform_with(selector, vec)
    return (Workflow().set_input_frame(frame)
            .set_result_features(pred).train())


def _selector(checkpoint_dir=None, single=False):
    fams = [(OpLogisticRegression(max_iter=25),
             [{"reg_param": r} for r in (0.01, 0.1)])]
    if not single:
        fams.append((OpGBTClassifier(num_rounds=4, max_depth=2),
                     [{"learning_rate": lr} for lr in (0.1, 0.3)]))
    return BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, seed=1, models_and_parameters=fams,
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1),
        checkpoint_dir=checkpoint_dir)


def _assert_summaries_equal(s1, s2):
    assert s1.best_model_name == s2.best_model_name
    v1 = {r.model_name: r.metric_values for r in s1.validation_results}
    v2 = {r.model_name: r.metric_values for r in s2.validation_results}
    assert set(v1) == set(v2)
    for k in v1:
        for m in v1[k]:
            assert v1[k][m] == v2[k][m], (k, m)


@pytest.fixture(autouse=True)
def _stacked_on(monkeypatch):
    monkeypatch.setenv("TRANSMOGRIFAI_SWEEP_STACKED", "1")
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")


# ---------------------------------------------------------------------------
# classifiers (the shared cause-chain walk)
# ---------------------------------------------------------------------------

def test_oom_classifier_walks_cause_chain():
    oom = _oom_error()
    assert resources.is_resource_exhausted(oom)
    assert not is_transient_device_error(oom)  # never same-shape retried
    # wrapped cause: still classified
    try:
        try:
            raise oom
        except Exception as e:
            raise ValueError("layer wrap") from e
    except ValueError as wrapped:
        assert resources.is_resource_exhausted(wrapped)
    # implicit context (raise-while-handling): still classified
    try:
        try:
            raise oom
        except Exception:
            raise KeyError("handler blew up")
    except KeyError as ctx:
        assert resources.is_resource_exhausted(ctx)
    # `raise ... from None` severs the chain — honored
    try:
        try:
            raise oom
        except Exception:
            raise ValueError("deliberately severed") from None
    except ValueError as severed:
        assert not resources.is_resource_exhausted(severed)
    # host allocation failure is unambiguous
    assert resources.is_resource_exhausted(MemoryError())
    # exact type names only: RuntimeError subclasses never match
    assert not resources.is_resource_exhausted(
        NotImplementedError("Out of memory"))
    # transient stays transient, OOM stays OOM — disjoint marker sets
    transient = XlaRuntimeError("UNAVAILABLE: flaky tunnel")
    assert is_transient_device_error(transient)
    assert not resources.is_resource_exhausted(transient)


def test_disk_full_classifier():
    e = OSError(errno.ENOSPC, "No space left on device")
    assert resources.is_disk_full(e)
    assert not resources.is_disk_full(OSError("plain IO error"))
    assert not resources.is_disk_full(_oom_error())
    try:
        try:
            raise e
        except OSError as inner:
            raise RuntimeError("checkpoint failed") from inner
    except RuntimeError as wrapped:
        assert resources.is_disk_full(wrapped)


# ---------------------------------------------------------------------------
# fault kinds
# ---------------------------------------------------------------------------

def test_oom_and_enospc_fault_kinds():
    spec = FaultSpec.parse("oom@sweep.fit#1x2")
    assert (spec.kind, spec.at, spec.times) == ("oom", 1, 2)
    plan = FaultPlan(["oom@sweep.fit#1x2", "enospc@checkpoint.write"])
    # invocation 0 clean, 1 and 2 fire, 3 clean
    plan.check("sweep.fit")
    for _ in range(2):
        with pytest.raises(XlaRuntimeError) as ei:
            plan.check("sweep.fit")
        assert resources.is_resource_exhausted(ei.value)
        assert not is_transient_device_error(ei.value)
    plan.check("sweep.fit")
    with pytest.raises(OSError) as oi:
        plan.check("checkpoint.write")
    assert oi.value.errno == errno.ENOSPC
    assert resources.is_disk_full(oi.value)
    assert plan.fired == [("sweep.fit", 1, "oom"), ("sweep.fit", 2, "oom"),
                          ("checkpoint.write", 0, "enospc")]


# ---------------------------------------------------------------------------
# sweep rungs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sweep_frame():
    return _frame()


@pytest.fixture(scope="module")
def loop_summary(sweep_frame):
    """The per-fold-loop reference run every rung's result must match
    bitwise."""
    saved = {k: os.environ.get(k) for k in ("TRANSMOGRIFAI_SWEEP_STACKED",
                                            "TRANSMOGRIFAI_TREE_STACKED")}
    os.environ["TRANSMOGRIFAI_SWEEP_STACKED"] = "0"
    os.environ["TRANSMOGRIFAI_TREE_STACKED"] = "0"
    try:
        return _train(_selector(), sweep_frame).selector_summary()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_stacked_family_oom_degrades_to_fold_loop(sweep_frame,
                                                 loop_summary):
    """An OOM at the LR family's stacked dispatch re-dispatches that
    family on the per-fold loop: the run completes, the winner and every
    validation metric are bitwise those of the loop path, and the rung
    is counted once at its site."""
    with fault_plan("oom@sweep.fit#0"):
        with pytest.warns(RuntimeWarning, match="degrading to rung"):
            s = _train(_selector(), sweep_frame).selector_summary()
    rc = resource_counters.to_json()
    assert rc["degradationsBySite"] == {"sweep.stacked": 1}
    assert rc["oomEvents"] == 1
    assert not s.failures
    _assert_summaries_equal(s, loop_summary)


def test_tree_group_oom_halves_lane_chunks(sweep_frame, loop_summary,
                                           tmp_path):
    """An OOM at the GBT depth-group's stacked chunk (invocation 1: the
    LR family dispatched clean at 0) halves the lane-chunk width and
    retries the SAME lanes — only that group degrades, the LR family
    stays on its stacked path, metrics stay bitwise, and the sweep
    checkpoint records the rung."""
    ckpt = str(tmp_path / "sweep_ckpt")
    with fault_plan("oom@sweep.fit#1"):
        with pytest.warns(RuntimeWarning, match="degrading to rung"):
            s = _train(_selector(checkpoint_dir=ckpt),
                       sweep_frame).selector_summary()
    rc = resource_counters.to_json()
    assert rc["degradationsBySite"] == {"sweep.tree_group": 1}
    assert not s.failures
    _assert_summaries_equal(s, loop_summary)
    # the checkpoint records WHICH shape ran degraded, at which rung
    with open(os.path.join(ckpt, "sweep.json")) as fh:
        doc = json.load(fh)
    degs = doc.get("degradations")
    assert degs and degs[0]["site"] == "sweep.tree_group"
    assert degs[0]["rung"].startswith("lane_chunk_")
    # the LR family was untouched by the tree group's rung
    from transmogrifai_tpu.utils.profiling import sweep_counters
    lr = sweep_counters.families.get("OpLogisticRegression_0")
    assert lr is not None and lr.mode == "fold_stacked"


def test_settle_oom_collects_family_for_fold_retry(sweep_frame):
    """A settle-time OOM (device pressure that materializes only when
    the overlapped programs run) routes the family into the caller's
    ``oom_retry`` list instead of a failure record, popping its partial
    scores."""
    class _OomOnMaterialize:
        def __array__(self, dtype=None):
            raise _oom_error()

    sel = _selector(single=True)
    per_scores = {(0, 0): [0.5], (0, 1): [0.6]}
    failures: list = []
    oom_retry: list = []
    pending = [{"kind": "stacked", "ci": 0, "fname": "LR_0",
                "key": "0:stacked:3x100x2", "k": 3, "grid_len": 2,
                "chunks": [(0, 2, _OomOnMaterialize())]}]
    with pytest.warns(RuntimeWarning, match="degrading to rung"):
        sel._settle(pending, {}, per_scores, failures,
                    oom_retry=oom_retry)
    assert oom_retry == [0]
    assert failures == []
    assert per_scores == {}
    # without the ladder the same settle failure records a failure
    resource_counters.reset()
    os.environ["TRANSMOGRIFAI_RESOURCE_LADDER"] = "0"
    try:
        pending[0]["chunks"] = [(0, 2, _OomOnMaterialize())]
        oom_retry2: list = []
        sel._settle(pending, {}, {(0, 0): [0.5]}, failures,
                    oom_retry=oom_retry2)
        assert oom_retry2 == [] and len(failures) == 1
        assert resource_counters.to_json()["degradations"] == 0
    finally:
        del os.environ["TRANSMOGRIFAI_RESOURCE_LADDER"]


def test_ladder_disabled_sweep_fault_fails_fast(sweep_frame,
                                                monkeypatch):
    """With the ladder off, the identical injected OOM keeps its
    pre-ladder behavior exactly: candidate-failure isolation (and a
    single-family selector raises), zero rungs counted."""
    monkeypatch.setenv("TRANSMOGRIFAI_RESOURCE_LADDER", "0")
    with fault_plan("oom@sweep.fit#0"):
        s = _train(_selector(), sweep_frame).selector_summary()
    assert any("RESOURCE_EXHAUSTED" in f.get("reason", "")
               for f in s.failures)
    assert resource_counters.to_json()["degradations"] == 0
    with fault_plan("oom@sweep.fit#0x*"):
        with pytest.raises(RuntimeError, match="every candidate failed"):
            _train(_selector(single=True), sweep_frame)


def test_refit_warm_oom_falls_back_cold(sweep_frame):
    """An OOM inside the warm-started winner refit releases the retained
    fold parameters and refits cold (bitwise the TRANSMOGRIFAI_REFIT_WARM=0
    refit) instead of dying after a completed sweep."""
    os.environ["TRANSMOGRIFAI_REFIT_WARM"] = "0"
    try:
        s_cold = _train(_selector(single=True),
                        sweep_frame).selector_summary()
    finally:
        del os.environ["TRANSMOGRIFAI_REFIT_WARM"]
    resource_counters.reset()
    # single LR family: sweep.fit#0 is the stacked sweep dispatch,
    # #1 is the refit unit
    with fault_plan("oom@sweep.fit#1"):
        with pytest.warns(RuntimeWarning, match="degrading to rung"):
            s = _train(_selector(single=True),
                       sweep_frame).selector_summary()
    rc = resource_counters.to_json()
    assert rc["degradationsBySite"] == {"selector.refit": 1}
    assert s.best_model_name == s_cold.best_model_name
    for k in s.train_evaluation:
        assert s.train_evaluation[k] == s_cold.train_evaluation[k]


# ---------------------------------------------------------------------------
# serving rungs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served():
    UID.reset()
    n = 160
    rng = np.random.default_rng(3)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (rng.uniform(size=n)
         < 1 / (1 + np.exp(-(1.5 * x1 - x2)))).astype(float)
    frame = fr.HostFrame.from_dict({
        "y": (ft.RealNN, y.tolist()),
        "x1": (ft.Real, x1.tolist()),
        "x2": (ft.Real, x2.tolist()),
    })
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify([feats["x1"], feats["x2"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=25), [{}])])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    rows = [{"x1": float(x1[i]), "x2": float(x2[i])} for i in range(n)]
    return model, rows


def test_serving_oom_sheds_buckets_zero_drops(served):
    """A mid-traffic OOM sheds the largest padding bucket and re-serves
    the batch compiled at the smaller shape: zero dropped requests, zero
    failed futures, NO row-path degradation, and the rung observable in
    counters + the flight recorder."""
    from transmogrifai_tpu.serving import ScoringServer
    from transmogrifai_tpu.utils.events import events
    model, rows = served
    events.reset()
    srv = ScoringServer(model, max_batch=32, min_bucket=8,
                        max_wait_ms=1.0)
    srv.start(warmup_row=rows[0])
    assert srv.scorer.buckets == [8, 16, 32]
    with fault_plan("oom@serving.dispatch#1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            futs = [srv.submit(dict(r)) for r in rows[:60]]
            results = [f.result(timeout=30) for f in futs]
    srv.stop()
    assert all(isinstance(r, dict) for r in results)
    assert srv.scorer.buckets == [8, 16]
    assert srv.scorer.max_batch == 16
    snap = srv.metrics.snapshot(mirror_to_profiler=False)
    assert snap["requests"]["failed"] == 0
    assert snap["requests"]["completed"] == 60
    assert snap["degraded"]["entries"] == 0  # compiled path, narrower
    rc = resource_counters.to_json()
    assert rc["degradationsBySite"].get("serving.dispatch", 0) >= 1
    degr = [e for e in events.tail() if e["kind"] == "resource.degrade"]
    assert degr and degr[0]["site"] == "serving.dispatch"
    assert degr[0]["rung"] == "shed_bucket_32"


def test_serving_shed_floor_falls_to_row_path(served):
    """OOM with only one bucket left exhausts the rungs: the row path
    serves (pre-existing degradation), still zero drops."""
    from transmogrifai_tpu.serving import ScoringServer
    model, rows = served
    srv = ScoringServer(model, max_batch=8, min_bucket=8,
                        max_wait_ms=1.0)
    srv.start(warmup_row=rows[0])
    assert srv.scorer.buckets == [8]
    with fault_plan("oom@serving.dispatch#1x*"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            futs = [srv.submit(dict(r)) for r in rows[:20]]
            results = [f.result(timeout=30) for f in futs]
    srv.stop()
    assert all(isinstance(r, dict) for r in results)
    snap = srv.metrics.snapshot(mirror_to_profiler=False)
    assert snap["requests"]["failed"] == 0
    assert snap["degraded"]["entries"] >= 1  # floor reached: row path
    assert srv.scorer.buckets == [8]  # nothing left to shed


def test_shed_success_exits_degraded_mode(served):
    """An OOM on a degraded-mode PROBE batch that the shed rung recovers
    clears degraded mode immediately (recovery recorded) — the server
    must not pin traffic on the row path for another probe interval
    after the compiled path just proved good at the smaller shape."""
    import time as _time
    from transmogrifai_tpu.serving import ScoringServer
    model, rows = served
    srv = ScoringServer(model, max_batch=32, min_bucket=8,
                        max_wait_ms=1.0)
    srv.start(warmup_row=rows[0])
    srv._degraded_since = _time.monotonic() - 5.0  # degraded, probe due
    srv._last_probe = 0.0
    srv.metrics.record_degraded_entry()
    with fault_plan("oom@serving.dispatch#0"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r = srv.score(dict(rows[0]), timeout_s=30)
    srv.stop()
    assert isinstance(r, dict)
    assert not srv.degraded
    assert srv.metrics.snapshot(
        mirror_to_profiler=False)["degraded"]["recoveries"] >= 1


def test_serving_ladder_off_keeps_old_behavior(served, monkeypatch):
    """Ladder off + the same OOM = the pre-ladder contract exactly:
    row-path degradation, buckets untouched, zero rungs."""
    from transmogrifai_tpu.serving import ScoringServer
    monkeypatch.setenv("TRANSMOGRIFAI_RESOURCE_LADDER", "0")
    model, rows = served
    srv = ScoringServer(model, max_batch=32, min_bucket=8,
                        max_wait_ms=1.0)
    srv.start(warmup_row=rows[0])
    with fault_plan("oom@serving.dispatch#1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            futs = [srv.submit(dict(r)) for r in rows[:40]]
            results = [f.result(timeout=30) for f in futs]
    srv.stop()
    assert all(isinstance(r, dict) for r in results)
    assert srv.scorer.buckets == [8, 16, 32]
    snap = srv.metrics.snapshot(mirror_to_profiler=False)
    assert snap["requests"]["failed"] == 0
    assert snap["degraded"]["entries"] >= 1
    assert resource_counters.to_json()["degradations"] == 0


def test_program_cache_pressure_eviction():
    """evict_cold frees LRU-oldest entries (never the last one) and
    evict_bucket drops one (model, bucket) slice, both attributing
    evictions to the owners' counters."""
    from transmogrifai_tpu.serving.fleet import ProgramCache
    from transmogrifai_tpu.utils.profiling import ServingCounters
    cache = ProgramCache()
    c = ServingCounters()
    for i, b in enumerate((8, 16, 32)):
        cache.get(("fp", 0, b), lambda: object(), bytes_est=100,
                  counters=c, bucket=b)
        cache.get(("fp2", 0, b), lambda: object(), bytes_est=100,
                  counters=c, bucket=b)
    assert len(cache) == 6 and cache.current_bytes == 600
    freed = cache.evict_cold(250)
    assert freed == 300 and len(cache) == 3
    assert cache.evictions == 3
    n = cache.evict_bucket("fp2", 32)
    assert n == 1
    assert ("fp2", 0, 32) not in cache.keys()
    # never evicts the last entry under pressure
    cache2 = ProgramCache()
    cache2.get(("fp", 0, 8), lambda: object(), bytes_est=100,
               counters=c, bucket=8)
    assert cache2.evict_cold(10**9) == 0 and len(cache2) == 1
    # evictions attributed per bucket: the LRU pass dropped both 8s and
    # one 16; evict_bucket dropped one 32
    assert c.bucket(8).evictions == 2
    assert c.bucket(16).evictions == 1
    assert c.bucket(32).evictions == 1


# ---------------------------------------------------------------------------
# ENOSPC: counted best-effort writes + spill accounting
# ---------------------------------------------------------------------------

def test_enospc_checkpoint_write_counts_and_backs_off():
    from transmogrifai_tpu.utils.durable import best_effort_checkpoint_write
    calls = []

    def full_disk():
        calls.append(1)
        raise OSError(errno.ENOSPC, "No space left on device")

    with pytest.warns(RuntimeWarning, match="No space left"):
        assert best_effort_checkpoint_write(full_disk, "ckpt write") \
            is False
    rc = resource_counters.to_json()
    assert rc["enospcEvents"] == 1
    assert resource_counters.enospc_backoff_active()
    # inside the cooldown: the write is SKIPPED (counted), not attempted
    assert best_effort_checkpoint_write(full_disk, "ckpt write") is False
    assert len(calls) == 1
    assert resource_counters.to_json()["writesSkipped"] == 1
    # a non-ENOSPC failure neither counts nor arms the backoff
    resource_counters.reset()

    def plain_fail():
        raise OSError("unrelated")

    with pytest.warns(RuntimeWarning):
        best_effort_checkpoint_write(plain_fail, "ckpt write")
    rc = resource_counters.to_json()
    assert rc["enospcEvents"] == 0
    assert not resource_counters.enospc_backoff_active()


def test_enospc_event_spill_counted_never_raises(tmp_path):
    """ENOSPC inside the spill writer loses the batch ACCOUNTED
    (spill_lost + resource enospc counters), never raises into the
    serving path — and does NOT arm the durable-write cooldown (the
    spill's volume may not be the checkpoint volume; checkpoint writes
    re-detect their own ENOSPC)."""
    from transmogrifai_tpu.utils.events import EventRing
    ring = EventRing(maxlen=64)
    ring.configure(spill_path=str(tmp_path / "events.jsonl"))
    try:
        with fault_plan("enospc@events.spill#0"):
            ring.emit("test.event", n=1)
            ring.flush()  # hits the injected ENOSPC; must not raise
        assert ring.spill_lost >= 1
        assert resource_counters.to_json()["enospcEvents"] >= 1
        assert not resource_counters.enospc_backoff_active()
        # the spill recovers on the next drain (new batch, reopened file)
        ring.emit("test.event", n=2)
        ring.flush()
        assert ring.spilled >= 1
    finally:
        ring.configure(spill_path=None)


# ---------------------------------------------------------------------------
# continuous loop: retrain window shrink
# ---------------------------------------------------------------------------

def test_continuous_retrain_oom_shrinks_window(tmp_path):
    """An OOM-failed retrain halves the row window for the backed-off
    retry and keeps the pending record (old model keeps serving, no
    abandonment); the capped retry trains on the newest half."""
    from transmogrifai_tpu.continuous import ContinuousLoop
    UID.reset()
    rng = np.random.default_rng(0)
    n = 120
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (rng.uniform(size=n)
         < 1 / (1 + np.exp(-(1.5 * x1 - x2)))).astype(float)
    host = fr.HostFrame.from_dict({
        "label": (ft.RealNN, y.tolist()),
        "x1": (ft.Real, x1.tolist()),
        "x2": (ft.Real, x2.tolist()),
    })
    feats = FeatureBuilder.from_frame(host, response="label")
    vec = transmogrify([feats["x1"], feats["x2"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=20), [{}])])
    pred = feats["label"].transform_with(sel, vec)
    wf = Workflow().set_input_frame(host).set_result_features(pred, vec)

    loop = ContinuousLoop(
        wf, stream_dir=str(tmp_path / "stream"),
        state_dir=str(tmp_path / "state"), window_batches=1,
        poll_interval_s=0.05, timeout_s=0.1)
    rows = [{"label": float(y[i]), "x1": float(x1[i]),
             "x2": float(x2[i])} for i in range(n)]
    loop._rows_by_source["b0.csv"] = rows
    loop.state.record_batch("b0.csv", len(rows), 8)
    loop.state.begin_retrain(["test"], str(tmp_path / "ckpt"))
    with fault_plan("oom@continuous.retrain#0"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert loop._execute_retrain() is False
    assert loop._retrain_row_cap == len(rows) // 2
    assert loop.state.pending_retrain is not None  # NOT abandoned
    rc = resource_counters.to_json()
    assert rc["degradationsBySite"].get("continuous.retrain") == 1
    assert loop.metrics.retrain_failures == 1
    assert loop._window_rows(loop.state.pending_retrain) == \
        rows[-(len(rows) // 2):]
    # the capped retry trains and promotes (bootstrap registration),
    # which resets the cap for the next full window
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert loop._execute_retrain() is True
    try:
        assert loop.fleet.registry.active_version("live") is not None
        assert loop._retrain_row_cap is None
    finally:
        loop.fleet.stop()


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------

def test_resource_prometheus_series_and_health(served):
    from transmogrifai_tpu.serving import ScoringServer
    from transmogrifai_tpu.utils.prometheus import build_registry
    resource_counters.note_degradation("serving.dispatch")
    resource_counters.note_oom()
    text = build_registry(include_app=False).render()
    assert ('transmogrifai_resource_degradations_total'
            '{site="serving.dispatch"} 1') in text
    assert "transmogrifai_resource_oom_events_total 1" in text
    assert "transmogrifai_resource_rss_bytes" in text
    assert "transmogrifai_resource_ladder_enabled 1" in text
    assert "# collect failed" not in text
    model, rows = served
    srv = ScoringServer(model, max_batch=8)
    doc = srv.health()
    res = doc["resources"]
    assert res["ladderEnabled"] is True
    assert res["counters"]["degradations"] == 1
    assert isinstance(res["rssBytes"], int)


def test_pressure_state_budgets_and_watchdog(monkeypatch):
    state = resources.pressure_state()
    assert state["rssPressure"] is False  # no budget configured
    assert state["rssBytes"] > 0
    monkeypatch.setenv("TRANSMOGRIFAI_RSS_BUDGET", "1")
    monkeypatch.setenv("TRANSMOGRIFAI_DISK_MIN_FREE", "1")
    state = resources.pressure_state()
    assert state["rssPressure"] is True
    assert state["diskPressure"] is False  # plenty of disk vs 1 byte
    wd = resources.ResourceWatchdog(".", interval_s=0.01)
    from transmogrifai_tpu.utils.events import events
    events.reset()
    with pytest.warns(RuntimeWarning, match="host resource pressure"):
        sample = wd.tick()
    assert sample["rssPressure"] is True
    assert any(e["kind"] == "resource.pressure" for e in events.tail())
    # second tick in the same pressured state: no duplicate event
    n_events = len(events.tail())
    wd.tick()
    assert len(events.tail()) == n_events


def test_watch_path_points_probes_at_write_root(tmp_path):
    """Daemons point the default pressure probes at their write root —
    the /healthz and gauge disk numbers must describe the filesystem
    the process writes, not the cwd's."""
    saved = resources.watch_path()
    try:
        resources.set_watch_path(str(tmp_path))
        assert resources.watch_path() == str(tmp_path)
        assert resources.disk_free_bytes() > 0
        assert resources.pressure_state()["diskFreeBytes"] > 0
        # a bogus watch path degrades to the -1 probe-failed sentinel,
        # never a raise in a health endpoint
        resources.set_watch_path(str(tmp_path / "nope"))
        assert resources.pressure_state()["diskFreeBytes"] == -1
    finally:
        resources.set_watch_path(saved)


def test_run_summary_carries_resource_counters():
    from transmogrifai_tpu.utils.profiling import AppMetrics
    resource_counters.note_degradation("sweep.stacked")
    doc = AppMetrics().to_json()
    assert doc["resourceCounters"]["degradations"] == 1
    assert doc["resourceCounters"]["degradationsBySite"] == {
        "sweep.stacked": 1}


def test_failure_lint_rejects_adhoc_classifier(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import check_failure_paths as lint
    bad = tmp_path / "handler.py"
    bad.write_text(
        "def f(e):\n"
        "    if 'RESOURCE_EXHAUSTED' in str(e):\n"
        "        return True\n")
    out = lint.check_file(str(bad))
    assert out and "ad-hoc resource-exhaustion" in out[0]
    ok = tmp_path / "resources.py"
    ok.write_text(
        "def f(e):\n"
        "    return 'RESOURCE_EXHAUSTED' in str(e)\n")
    assert lint.check_file(str(ok)) == []
    # the live tree stays clean
    pkg_root = os.path.join(os.path.dirname(__file__), "..",
                            "transmogrifai_tpu")
    assert [v for v in lint.check_tree(pkg_root)
            if "ad-hoc" in v] == []
