"""bench.py measurement-artifact machinery (pure host logic, no jax).

The driver depends on bench.py's always-print-JSON contract; these pin
the artifact loaders' validation (rows/models match, malformed content
tolerated, stale code fingerprints rejected) and the atomic saver.
"""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def benchmod():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _accel_art(m, **over):
    art = {"metric": "x", "rows": m.N_ROWS, "models": m.MODELS,
           "platform": "tpu", "wall_s": 1234.5, "holdout_auroc": 0.82,
           "best_model": "g", "phases": {}, "scaling_curve": [],
           "code_fingerprint": m._code_fingerprint(),
           "measured_at": "2026-07-31T00:00:00Z"}
    art.update(over)
    return art


def test_accel_artifact_roundtrip_and_rejections(benchmod, tmp_path,
                                                 monkeypatch):
    m = benchmod
    path = str(tmp_path / "ACCEL.json")
    monkeypatch.setattr(m, "_accel_artifact_path", lambda: path)

    # save is atomic and loads back
    m._save_accel_artifact({"wall": 1234.5, "platform": "tpu",
                            "auroc": 0.82, "best": "g"}, [])
    got = m._load_accel_artifact()
    assert got is not None and got["wall_s"] == 1234.5
    assert got["code_fingerprint"] == m._code_fingerprint()

    # stale code fingerprint -> rejected
    json.dump(_accel_art(m, code_fingerprint="deadbeef0000"),
              open(path, "w"))
    assert m._load_accel_artifact() is None
    # CPU platform -> rejected (accel artifact must be an accel wall)
    json.dump(_accel_art(m, platform="cpu"), open(path, "w"))
    assert m._load_accel_artifact() is None
    # rows mismatch -> rejected
    json.dump(_accel_art(m, rows=m.N_ROWS + 1), open(path, "w"))
    assert m._load_accel_artifact() is None
    # malformed content must never raise (always-print-JSON contract)
    open(path, "w").write("{not json")
    assert m._load_accel_artifact() is None
    json.dump(["not", "a", "dict"], open(path, "w"))
    assert m._load_accel_artifact() is None
    json.dump(_accel_art(m, wall_s=None), open(path, "w"))
    assert m._load_accel_artifact() is None
    os.remove(path)
    assert m._load_accel_artifact() is None


def test_cpu_artifact_validation(benchmod, tmp_path):
    m = benchmod
    path = str(tmp_path / "CPU.json")
    art = {"rows": m.N_ROWS, "models": m.MODELS, "wall_s": 4253.89,
           "platform": "cpu"}
    json.dump(art, open(path, "w"))
    got = m._load_bench_artifact(path, accel_only=False)
    assert got is not None and got["wall_s"] == 4253.89
    # the CPU loader does NOT demand a fingerprint (hand-committed,
    # code drift is acceptable for the baseline side) but still
    # validates rows/models
    json.dump({**art, "models": "lr"}, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False) is None


def test_code_fingerprint_tracks_sources(benchmod):
    m = benchmod
    fp = m._code_fingerprint()
    assert isinstance(fp, str) and len(fp) == 12
    assert fp == m._code_fingerprint()  # deterministic


def test_cpu_artifact_requires_cpu_platform(benchmod, tmp_path):
    """The vs_baseline DENOMINATOR must be a real CPU measurement: an
    accelerator artifact (or one missing the platform field) dropped into
    the CPU slot is rejected (ADVICE r5)."""
    m = benchmod
    path = str(tmp_path / "CPU.json")
    art = {"rows": m.N_ROWS, "models": m.MODELS, "wall_s": 4253.89,
           "platform": "cpu"}
    json.dump(art, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False,
                                  require_platform="cpu") is not None
    json.dump({**art, "platform": "tpu"}, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False,
                                  require_platform="cpu") is None
    art.pop("platform")
    json.dump(art, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False,
                                  require_platform="cpu") is None


def test_device_breakdown_surfaces_sweep_counters(benchmod):
    m = benchmod
    counters = {"OpLogisticRegression_0": {
        "mode": "fold_stacked", "compiles": 7,
        "deviceDispatches": 1, "hostSyncs": 1}}
    out = m._device_breakdown({"phases": {}, "sweep_counters": counters})
    assert out["sweep"] == counters
    assert "sweep" not in m._device_breakdown({"phases": {}})
