"""bench.py measurement-artifact machinery (pure host logic, no jax).

The driver depends on bench.py's always-print-JSON contract; these pin
the artifact loaders' validation (rows/models match, malformed content
tolerated, stale code fingerprints rejected) and the atomic saver. The
second half wires ``scripts/check_artifacts.py`` into tier-1: every
COMMITTED ``benchmarks/*.json`` must pass schema validation, so a "cited
but never committed" (or key-starved) artifact fails loudly.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name.replace(".py", "").replace("/", "_"),
        os.path.join(REPO, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def benchmod():
    return _load_script("bench.py")


@pytest.fixture()
def checker():
    return _load_script("scripts/check_artifacts.py")


def _accel_art(m, **over):
    art = {"metric": "x", "rows": m.N_ROWS, "models": m.MODELS,
           "platform": "tpu", "wall_s": 1234.5, "holdout_auroc": 0.82,
           "best_model": "g", "phases": {}, "scaling_curve": [],
           "code_fingerprint": m._code_fingerprint(),
           "measured_at": "2026-07-31T00:00:00Z"}
    art.update(over)
    return art


def test_accel_artifact_roundtrip_and_rejections(benchmod, tmp_path,
                                                 monkeypatch):
    m = benchmod
    path = str(tmp_path / "ACCEL.json")
    monkeypatch.setattr(m, "_accel_artifact_path", lambda: path)

    # save is atomic and loads back
    m._save_accel_artifact({"wall": 1234.5, "platform": "tpu",
                            "auroc": 0.82, "best": "g"}, [])
    got = m._load_accel_artifact()
    assert got is not None and got["wall_s"] == 1234.5
    assert got["code_fingerprint"] == m._code_fingerprint()

    # stale code fingerprint -> rejected
    json.dump(_accel_art(m, code_fingerprint="deadbeef0000"),
              open(path, "w"))
    assert m._load_accel_artifact() is None
    # CPU platform -> rejected (accel artifact must be an accel wall)
    json.dump(_accel_art(m, platform="cpu"), open(path, "w"))
    assert m._load_accel_artifact() is None
    # rows mismatch -> rejected
    json.dump(_accel_art(m, rows=m.N_ROWS + 1), open(path, "w"))
    assert m._load_accel_artifact() is None
    # malformed content must never raise (always-print-JSON contract)
    open(path, "w").write("{not json")
    assert m._load_accel_artifact() is None
    json.dump(["not", "a", "dict"], open(path, "w"))
    assert m._load_accel_artifact() is None
    json.dump(_accel_art(m, wall_s=None), open(path, "w"))
    assert m._load_accel_artifact() is None
    os.remove(path)
    assert m._load_accel_artifact() is None


def test_cpu_artifact_validation(benchmod, tmp_path):
    m = benchmod
    path = str(tmp_path / "CPU.json")
    art = {"rows": m.N_ROWS, "models": m.MODELS, "wall_s": 4253.89,
           "platform": "cpu"}
    json.dump(art, open(path, "w"))
    got = m._load_bench_artifact(path, accel_only=False)
    assert got is not None and got["wall_s"] == 4253.89
    # the CPU loader does NOT demand a fingerprint (hand-committed,
    # code drift is acceptable for the baseline side) but still
    # validates rows/models
    json.dump({**art, "models": "lr"}, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False) is None


def test_code_fingerprint_tracks_sources(benchmod):
    m = benchmod
    fp = m._code_fingerprint()
    assert isinstance(fp, str) and len(fp) == 12
    assert fp == m._code_fingerprint()  # deterministic


def test_cpu_artifact_requires_cpu_platform(benchmod, tmp_path):
    """The vs_baseline DENOMINATOR must be a real CPU measurement: an
    accelerator artifact (or one missing the platform field) dropped into
    the CPU slot is rejected (ADVICE r5)."""
    m = benchmod
    path = str(tmp_path / "CPU.json")
    art = {"rows": m.N_ROWS, "models": m.MODELS, "wall_s": 4253.89,
           "platform": "cpu"}
    json.dump(art, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False,
                                  require_platform="cpu") is not None
    json.dump({**art, "platform": "tpu"}, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False,
                                  require_platform="cpu") is None
    art.pop("platform")
    json.dump(art, open(path, "w"))
    assert m._load_bench_artifact(path, accel_only=False,
                                  require_platform="cpu") is None


def test_committed_artifacts_pass_schema(checker):
    """THE gate: every artifact committed under benchmarks/ validates."""
    findings = checker.check_dir(os.path.join(REPO, "benchmarks"))
    assert findings == {}, findings
    assert checker.main([os.path.join(REPO, "benchmarks")]) == 0


def test_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "m", "platform": "cpu", "rows": 10, "wall_s": 1.5}
    assert v(good) == []
    assert v({**good, "rows": None, "requests": 4096}) == []
    # rate-only artifacts (serving bench) validate via *_rps
    del good["wall_s"]
    assert v({**good, "batched_rps": 100.0}) == []
    assert any("timing" in e for e in v(good))
    assert any("metric" in e for e in v({"platform": "cpu", "rows": 1,
                                         "wall_s": 1.0}))
    assert any("platform" in e for e in v({"metric": "m", "rows": 1,
                                           "wall_s": 1.0}))
    assert any("rows" in e for e in v({"metric": "m", "platform": "cpu",
                                       "wall_s": 1.0}))
    assert any("rows" in e for e in v({"metric": "m", "platform": "cpu",
                                       "rows": True, "wall_s": 1.0}))
    assert v(["not", "a", "dict"]) == ["artifact is not a JSON object"]
    # accel artifacts demand provenance; CPU baselines are exempt
    accel = {"metric": "m", "platform": "tpu", "rows": 5, "wall_s": 2.0}
    assert any("code_fingerprint" in e for e in v(accel))
    assert v({**accel, "code_fingerprint": "abc123def456"}) == []


def test_artifact_checker_cli_fails_on_bad_dir(checker, tmp_path):
    bench = tmp_path / "benchmarks"
    bench.mkdir()
    (bench / "BAD.json").write_text('{"metric": "m"}')
    (bench / "BROKEN.json").write_text("{not json")
    findings = checker.check_dir(str(bench))
    assert set(findings) == {os.path.join("benchmarks", "BAD.json"),
                             os.path.join("benchmarks", "BROKEN.json")}
    assert any("unparseable" in e
               for e in findings[os.path.join("benchmarks", "BROKEN.json")])
    assert checker.main([str(bench)]) == 1


def test_serving_artifact_committed_and_healthy(checker):
    """The serving bench's acceptance contract, pinned on the COMMITTED
    artifact: >=10x micro-batched-jit-scorer vs row-closure throughput at
    batch 256 (engine vs engine — neither side queues), the end-to-end
    server number and latency percentiles recorded alongside, and 0
    post-warmup compiles per padding bucket."""
    path = os.path.join(REPO, "benchmarks", "SERVING.json")
    assert os.path.exists(path), "benchmarks/SERVING.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "online_serving_microbatch"
    assert art["max_batch"] == 256
    assert art["ok"] is True
    assert art["speedup"] >= 10.0               # scorer vs row closure
    assert art["scorer_rps"] > art["row_path_rps"]
    assert art["server_rps"] > art["row_path_rps"]  # end-to-end still wins
    for k in ("p50", "p95", "p99"):
        assert isinstance(art["latency_ms"][k], (int, float))
    assert art["buckets"], "per-bucket compile accounting missing"
    for b in art["buckets"]:
        assert b["post_warmup_compiles"] == 0, b
    assert art["parity_max_abs_diff"] < 1e-4


def test_tree_stacked_artifact_committed_and_healthy(checker):
    """The fold x grid-stacked tree sweep's acceptance contract, pinned
    on the COMMITTED artifact: the three-way comparison exists, the
    stacked path's metric parity vs the loop is within fp tolerance, and
    the structural dispatch/host-sync counts back the k x L-fewer-round-
    trips argument (stacked = 1 per group vs folds x grid_points)."""
    path = os.path.join(REPO, "benchmarks", "TREE_STACKED_SWEEP.json")
    assert os.path.exists(path), \
        "benchmarks/TREE_STACKED_SWEEP.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "tree_stacked_sweep"
    assert art["rows"] >= 100_000 and art["cols"] >= 28 \
        and art["bins"] >= 64
    assert art["metric_parity_stacked_vs_per_fold"] <= 1e-5
    hs = art["host_syncs"]
    assert hs["tree_stacked"] == art["groups"]
    assert hs["per_fold"] == art["folds"]
    assert hs["per_point"] == art["folds"] * art["grid_points"]


def test_tree_stacked_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "tree_stacked_sweep", "platform": "cpu",
            "rows": 100000, "tree_stacked_s": 1.0, "per_fold_s": 2.0,
            "per_point_s": 3.0, "speedup_vs_per_fold": 2.0,
            "speedup_vs_per_point": 3.0,
            "metric_parity_stacked_vs_per_fold": 0.0,
            "dispatches": {"tree_stacked": 1, "per_fold": 3,
                           "per_point": 12},
            "host_syncs": {"tree_stacked": 1, "per_fold": 3,
                           "per_point": 12}}
    assert v(good) == []
    assert any("parity" in e for e in v(
        {**good, "metric_parity_stacked_vs_per_fold": 0.5}))
    bad = dict(good)
    del bad["per_point_s"]
    assert any("per_point_s" in e for e in v(bad))
    assert any("host_syncs" in e for e in v(
        {**good, "host_syncs": {"tree_stacked": 1}}))


def test_one_sync_artifact_committed_and_healthy(checker):
    """Round 9's acceptance contract, pinned on the COMMITTED artifact:
    the async stacked sweep records exactly ONE blocking host sync for
    the whole train() (vs >= one per family on the per-family-settle
    leg), at least one refit actually warm-started, validation metrics
    are bit-equal across settle modes, and the warm refit's metrics are
    within 1e-5 of the cold serial refit."""
    path = os.path.join(REPO, "benchmarks", "ONE_SYNC_SWEEP.json")
    assert os.path.exists(path), \
        "benchmarks/ONE_SYNC_SWEEP.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "one_sync_sweep"
    syncs = art["total_host_syncs"]
    assert syncs["one_sync"] == 1 and syncs["one_sync_warm"] == 1
    assert syncs["per_family_settle"] >= art["families"] >= 2
    assert art["async_families"] == art["families"]
    assert art["refit_warm_starts"] >= 1
    assert art["validation_parity"] == 0.0
    assert art["refit_parity"] <= checker.MAX_REFIT_PARITY


def test_one_sync_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "one_sync_sweep", "platform": "cpu", "rows": 60000,
            "families": 2, "per_family_settle_s": 2.0, "one_sync_s": 1.0,
            "one_sync_warm_refit_s": 1.0, "speedup_vs_per_family": 2.0,
            "total_host_syncs": {"per_family_settle": 2, "one_sync": 1,
                                 "one_sync_warm": 1},
            "refit_warm_starts": 1, "validation_parity": 0.0,
            "refit_parity": 0.0}
    assert v(good) == []
    assert any("exactly 1" in e for e in v(
        {**good, "total_host_syncs": {"per_family_settle": 2,
                                      "one_sync": 2, "one_sync_warm": 1}}))
    assert any("per family" in e for e in v(
        {**good, "total_host_syncs": {"per_family_settle": 1,
                                      "one_sync": 1, "one_sync_warm": 1}}))
    assert any("warm" in e for e in v({**good, "refit_warm_starts": 0}))
    assert any("drifted" in e for e in v(
        {**good, "validation_parity": 1e-6}))
    assert any("parity" in e for e in v({**good, "refit_parity": 1e-3}))
    bad = dict(good)
    del bad["one_sync_warm_refit_s"]
    assert any("one_sync_warm_refit_s" in e for e in v(bad))


def test_serving_fleet_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "serving_fleet", "platform": "cpu",
            "requests": 30000, "models": 3, "aggregate_rps": 9000.0,
            "zero_dropped": True, "steady_p99_ms": 12.0,
            "p99_under_swap_ms": 18.0,
            "compile_storm": {"max_post_warmup_per_bucket": 0},
            "swap": {"wall_s": 0.4, "promoted": True},
            "cache": {"insertions": 12, "evictions": 0}}
    assert v(good) == []
    assert any("models" in e for e in v({**good, "models": 2}))
    assert any("zero_dropped" in e for e in v(
        {**good, "zero_dropped": False}))
    assert any("p99_under_swap_ms" in e for e in v(
        {k: x for k, x in good.items() if k != "p99_under_swap_ms"}))
    # the 2x zero-downtime latency bound
    assert any("2x steady-state" in e for e in v(
        {**good, "p99_under_swap_ms": 30.0}))
    # the compile-storm bound: any post-warmup compile is a violation
    assert any("compile-storm" in e for e in v(
        {**good, "compile_storm": {"max_post_warmup_per_bucket": 1}}))
    assert any("promote" in e for e in v(
        {**good, "swap": {"wall_s": 0.4, "promoted": False}}))
    assert any("cache" in e for e in v({**good, "cache": {}}))


def test_serving_fleet_artifact_committed_and_healthy(checker):
    """The fleet load test's acceptance contract, pinned on the
    COMMITTED artifact: >= 3 models behind one endpoint under sustained
    multi-process traffic, one mid-run hot-swap with zero dropped
    requests, p99-under-swap within 2x steady state, and a compile
    storm bounded at 0 post-warmup compiles per (model, bucket)."""
    path = os.path.join(REPO, "benchmarks", "SERVING_FLEET.json")
    assert os.path.exists(path), \
        "benchmarks/SERVING_FLEET.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "serving_fleet"
    assert art["models"] >= 3 and art["clients"] >= 2
    assert art["zero_dropped"] is True
    assert art["swap"]["promoted"] is True
    assert art["swap"]["shadow_rows"] > 0
    assert art["p99_under_swap_ms"] <= 2.0 * art["steady_p99_ms"]
    assert art["compile_storm"]["max_post_warmup_per_bucket"] == 0
    per_model = art["per_model"]
    assert len(per_model) >= 3
    for doc in per_model.values():
        assert doc["requests"] > 0
        assert isinstance(doc["p99_ms"], (int, float))


def test_device_breakdown_surfaces_sweep_counters(benchmod):
    m = benchmod
    counters = {"OpLogisticRegression_0": {
        "mode": "fold_stacked", "compiles": 7,
        "deviceDispatches": 1, "hostSyncs": 1}}
    out = m._device_breakdown({"phases": {}, "sweep_counters": counters})
    assert out["sweep"] == counters
    assert "sweep" not in m._device_breakdown({"phases": {}})


def test_continuous_loop_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "continuous_loop", "platform": "cpu", "rows": 600,
            "requests": 1500, "windows": 6, "drift_detected": True,
            "drift_score": 0.93, "retrain_wall_s": 2.1,
            "swap_wall_s": 0.7, "staleness_s": 2.8,
            "staleness_bound_s": 600.0, "zero_dropped": True,
            "zero_lost_rows": True,
            "promoted": {"version": "v2", "fromVersion": "v1"},
            "counters": {"driftTriggers": 1, "retrains": 1,
                         "promotions": 1, "rollbacks": 0}}
    assert v(good) == []
    assert any("drift_detected" in e for e in v(
        {**good, "drift_detected": False}))
    assert any("zero_dropped" in e for e in v(
        {**good, "zero_dropped": False}))
    assert any("zero_lost_rows" in e for e in v(
        {**good, "zero_lost_rows": False}))
    assert any("windows" in e for e in v({**good, "windows": 1}))
    assert any("staleness bound violated" in e for e in v(
        {**good, "staleness_s": 700.0}))
    assert any("retrain_wall_s" in e for e in v(
        {k: x for k, x in good.items() if k != "retrain_wall_s"}))
    assert any("drift_score" in e for e in v({**good, "drift_score": 0}))
    assert any("promoted" in e for e in v(
        {**good, "promoted": {"version": ""}}))
    assert any("counters" in e for e in v({**good, "counters": {}}))
    assert any("at least one" in e for e in v(
        {**good, "counters": {**good["counters"], "promotions": 0}}))


def test_continuous_loop_artifact_committed_and_healthy(checker):
    """The closed-loop acceptance contract, pinned on the COMMITTED
    artifact: an injected mid-stream covariate shift was detected, the
    retrain resumed serving traffic throughout, the hot-swap promoted a
    new version with zero dropped requests and zero lost/duplicated
    stream rows, within the staleness bound."""
    path = os.path.join(REPO, "benchmarks", "CONTINUOUS_LOOP.json")
    assert os.path.exists(path), \
        "benchmarks/CONTINUOUS_LOOP.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "continuous_loop"
    assert art["drift_detected"] is True
    assert art["zero_dropped"] is True and art["zero_lost_rows"] is True
    assert art["staleness_s"] <= art["staleness_bound_s"]
    assert art["promoted"]["version"] == "v2"
    assert art["promoted"]["fromVersion"] == "v1"
    assert art["promoted"]["shadowRows"] > 0  # the gate actually ran
    c = art["counters"]
    assert c["driftTriggers"] >= 1 and c["promotions"] >= 1
    assert c["rollbacks"] == 0
    assert art["requests"] > 0 and art["serving"]["errors"] == 0
    assert art["stream"]["rows"] == art["rows"]


def test_tracing_overhead_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "tracing_overhead", "platform": "cpu",
            "requests": 24576, "base_rps": 50000.0,
            "traced_rps": 48500.0, "overhead_pct": 3.0,
            "events_emitted": 2000, "spill_lines": 1990,
            "path_reconstructed": True}
    assert v(good) == []
    assert any("5% acceptance bound" in e for e in v(
        {**good, "overhead_pct": 5.1}))
    assert v({**good, "overhead_pct": -1.2}) == []  # traced leg faster
    assert any("overhead_pct" in e for e in v(
        {k: x for k, x in good.items() if k != "overhead_pct"}))
    assert any("base_rps" in e for e in v({**good, "base_rps": 0}))
    assert any("events_emitted" in e for e in v(
        {**good, "events_emitted": 0}))
    assert any("spill_lines" in e for e in v(
        {**good, "spill_lines": True}))
    assert any("path_reconstructed" in e for e in v(
        {**good, "path_reconstructed": False}))


def test_tracing_overhead_artifact_committed_and_healthy(checker):
    """The round-10 acceptance contract on the COMMITTED artifact:
    request tracing + flight-recorder emission + durable spill cost the
    serving hot path <= 5%, and the traced leg demonstrably traced (a
    sampled id greps to its full batch -> dispatch -> reply path)."""
    path = os.path.join(REPO, "benchmarks", "TRACING_OVERHEAD.json")
    assert os.path.exists(path), \
        "benchmarks/TRACING_OVERHEAD.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "tracing_overhead"
    assert art["ok"] is True and art["notes"] == []
    assert art["overhead_pct"] <= 5.0
    assert art["traced_rps"] > 0 and art["base_rps"] > 0
    assert len(art["overhead_trials_pct"]) == art["trials"] >= 3
    assert art["events_emitted"] > 0 and art["spill_lines"] > 0
    assert art["path_reconstructed"] is True


def test_resource_resilience_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = {"metric": "resource_resilience", "platform": "cpu",
            "rows": 4000, "requests": 400, "wall_s": 5.0,
            "sweep": {"completed": True, "winner_parity": 0.0,
                      "degradations": 2, "oom_injected": 2},
            "serving": {"requests": 400, "zero_dropped": True,
                        "degradations": 1, "buckets_shed": 1},
            "ladder_disabled_fails_fast": True,
            "counters": {"degradations": 3, "oomEvents": 3}}
    assert v(good) == []
    assert any("completed" in e for e in v(
        {**good, "sweep": {**good["sweep"], "completed": False}}))
    assert any("parity" in e for e in v(
        {**good, "sweep": {**good["sweep"], "winner_parity": 1e-3}}))
    assert any("degradations" in e for e in v(
        {**good, "sweep": {**good["sweep"], "degradations": 0}}))
    assert any("zero_dropped" in e for e in v(
        {**good, "serving": {**good["serving"], "zero_dropped": False}}))
    assert any("buckets_shed" in e for e in v(
        {**good, "serving": {**good["serving"], "buckets_shed": 0}}))
    assert any("fails_fast" in e.replace("fails fast", "fails_fast")
               or "ladder" in e for e in v(
        {**good, "ladder_disabled_fails_fast": False}))
    assert any("counters" in e for e in v(
        {**good, "counters": {"degradations": 3}}))
    assert any("'sweep' block" in e for e in v(
        {k: x for k, x in good.items() if k != "sweep"}))


def test_resource_resilience_artifact_committed_and_healthy(checker):
    """The round-11 acceptance contract on the COMMITTED artifact:
    injected OOMs mid-sweep and mid-serving cost degradation rungs, not
    the run — completed training with winner-metric parity <= 1e-5 vs
    the un-faulted run, zero dropped serving requests, and the
    ladder-off leg still failing fast (the ladder is additive)."""
    path = os.path.join(REPO, "benchmarks", "RESOURCE_RESILIENCE.json")
    assert os.path.exists(path), \
        "benchmarks/RESOURCE_RESILIENCE.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "resource_resilience"
    assert art["sweep"]["completed"] is True
    assert art["sweep"]["winner_parity"] <= 1e-5
    assert art["sweep"]["degradations"] >= 2  # both sweep rungs taken
    assert set(art["sweep"]["rungs"]) == {"sweep.stacked",
                                          "sweep.tree_group"}
    assert art["serving"]["zero_dropped"] is True
    assert art["serving"]["failed"] == 0
    assert art["serving"]["buckets_shed"] >= 1
    assert art["ladder_disabled_fails_fast"] is True
    assert art["counters"]["degradations"] >= 3
    assert art["counters"]["oomEvents"] >= 3


def _scaleout_good():
    return {
        "metric": "serving_scaleout", "platform": "cpu",
        "host_cpus": 2, "requests": 15000, "replicas": 4,
        "models": 4, "aggregate_rps": 640.0,
        "p50_ms": 10.0, "p99_ms": 60.0,
        "single_fleet": {"rps": 1100.0, "p50_ms": 5.0,
                         "p99_ms": 38.0, "clients": 8,
                         "requests": 11000},
        "scale_ratio": 0.58, "zero_dropped": True,
        "kill": {"replica": "r2", "at_s": 8.0, "zero_dropped": True,
                 "router_retries": 40, "router_markdowns": 5,
                 "respawned": True},
        "roll": {"model": "m1", "promoted": True,
                 "zero_downtime": True, "converged": True,
                 "wall_s": 0.9},
        "artifacts": {"mapped_replicas": 4, "replicas_seen": 4,
                      "post_warmup_compiles_max": 0},
    }


def test_serving_scaleout_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _scaleout_good()
    assert v(good) == []
    assert any("replicas" in e for e in v({**good, "replicas": 3}))
    assert any("zero_dropped" in e for e in v(
        {**good, "zero_dropped": False}))
    assert any("single_fleet" in e for e in v(
        {k: x for k, x in good.items() if k != "single_fleet"}))
    # the two-regime scale_ratio gate: a core-constrained host (2 cpus,
    # 4 replicas) holds the majority-throughput floor...
    assert any("core-constrained" in e for e in v(
        {**good, "scale_ratio": 0.2}))
    # ...an unconstrained host must prove sharding PAYS
    assert any("did not pay" in e for e in v(
        {**good, "host_cpus": 16, "scale_ratio": 0.9}))
    assert v({**good, "host_cpus": 16, "scale_ratio": 3.2}) == []
    # p99 flatness vs the matched-load single-fleet leg
    assert any("p99" in e for e in v({**good, "p99_ms": 100.0}))
    # the kill block: retries-not-drops + respawn are the contract
    assert any("respawned" in e for e in v(
        {**good, "kill": {**good["kill"], "respawned": False}}))
    # the roll block: zero global downtime + fleet convergence
    assert any("zero_downtime" in e for e in v(
        {**good, "roll": {**good["roll"], "zero_downtime": False}}))
    assert any("converged" in e for e in v(
        {**good, "roll": {**good["roll"], "converged": False}}))
    # compile-once-map-everywhere: every replica mapped, 0 post-warmup
    assert any("mapped" in e for e in v(
        {**good, "artifacts": {**good["artifacts"],
                               "mapped_replicas": 2}}))
    assert any("compile-storm" in e for e in v(
        {**good, "artifacts": {**good["artifacts"],
                               "post_warmup_compiles_max": 1}}))


def test_serving_scaleout_artifact_committed_and_healthy(checker):
    """The scale-out load test's acceptance contract, pinned on the
    COMMITTED artifact: >= 4 replica workers behind the router, a
    mid-run replica kill -9 absorbed as router retries (zero
    client-visible drops, victim respawned), a rolling promotion with
    zero global downtime converging every replica, and the shared
    program artifacts mapped by every replica with 0 post-warmup
    compiles."""
    path = os.path.join(REPO, "benchmarks", "SERVING_SCALEOUT.json")
    assert os.path.exists(path), \
        "benchmarks/SERVING_SCALEOUT.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "serving_scaleout"
    assert art["replicas"] >= 4 and art["models"] >= 3
    assert art["zero_dropped"] is True
    assert art["kill"]["respawned"] is True
    assert art["kill"]["router_retries"] >= 1
    assert art["roll"]["promoted"] and art["roll"]["converged"]
    assert art["roll"]["zero_downtime"] is True
    assert all(n > 0 for n in art["roll"]["success_buckets"])
    assert art["artifacts"]["mapped_replicas"] == art["replicas"]
    assert art["artifacts"]["post_warmup_compiles_max"] == 0
    assert art["single_fleet"]["rps"] > 0
    assert art["scale_ratio"] > 0


def _fe_fusion_good():
    return {
        "metric": "ingest_fe_fusion", "platform": "cpu", "rows": 200000,
        "value": 2.5, "unit": "s",
        "phases": {"build_s": 1.0, "fe_host_leg_s": 5.0,
                   "fe_fused_leg_s": 2.5, "overlap_wall_s": 3.0},
        "host_fe_wall_share": {"unfused_share": 0.55, "fused_share": 0.01,
                               "cut_ratio": 55.0},
        "parity": {"prediction_max_abs": 3e-7, "rows": 50000},
        "overlap": {"ratio": 0.4, "chunks": 8, "decode_s": 2.0,
                    "consumer_wait_s": 1.2, "wall_s": 3.0},
        "fused_disabled": {"fused_programs": 0, "bitwise_equal": True},
    }


def test_ingest_fe_fusion_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _fe_fusion_good()
    assert v(good) == []
    share = good["host_fe_wall_share"]
    assert any("cut_ratio" in e for e in v(
        {**good, "host_fe_wall_share": {**share, "cut_ratio": 2.0}}))
    assert any("unfused_share" in e for e in v(
        {**good, "host_fe_wall_share": {**share, "unfused_share": 0.0}}))
    assert any("prediction_max_abs" in e for e in v(
        {**good, "parity": {"prediction_max_abs": 1e-3}}))
    assert any("ratio" in e for e in v(
        {**good, "overlap": {**good["overlap"], "ratio": 1.5}}))
    assert any("chunks" in e for e in v(
        {**good, "overlap": {**good["overlap"], "chunks": 1}}))
    assert any("fused_programs" in e for e in v(
        {**good, "fused_disabled": {"fused_programs": 2,
                                    "bitwise_equal": True}}))
    assert any("bitwise" in e for e in v(
        {**good, "fused_disabled": {"fused_programs": 0,
                                    "bitwise_equal": False}}))
    assert any("phases" in e for e in v(
        {**good, "phases": {"build_s": 1.0}}))
    assert any("overlap" in e for e in v(
        {k: x for k, x in good.items() if k != "overlap"}))


def test_ingest_fe_fusion_artifact_committed_and_healthy(checker):
    """The round-14 acceptance contract on the COMMITTED artifact:
    host-side FE wall share cut >= 3x with fused-vs-unfused prediction
    parity <= 1e-5, a measured ingest/compute overlap ratio, and the
    TRANSMOGRIFAI_FE_FUSED=0 leg restoring the pre-fusion path
    byte-for-byte with zero fused programs (counter-asserted)."""
    path = os.path.join(REPO, "benchmarks", "INGEST_FE_FUSION.json")
    assert os.path.exists(path), \
        "benchmarks/INGEST_FE_FUSION.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "ingest_fe_fusion"
    assert art["host_fe_wall_share"]["cut_ratio"] >= checker.MIN_HOST_FE_CUT
    assert art["parity"]["prediction_max_abs"] <= checker.MAX_FE_FUSION_PARITY
    assert 0 <= art["overlap"]["ratio"] <= 1
    assert art["overlap"]["chunks"] >= 2
    assert art["fused_disabled"]["fused_programs"] == 0
    assert art["fused_disabled"]["bitwise_equal"] is True
    assert art["counters"]["fused_leg"]["feFusedPrograms"] >= 1


def _explain_overhead_good():
    return {
        "metric": "explain_overhead", "platform": "cpu", "requests": 2000,
        "plain_rps": 230.0, "explained_rps": 210.0,
        "plain": {"rps": 230.0, "p50_ms": 4.2, "p99_ms": 6.4},
        "explained": {"rps": 210.0, "p50_ms": 4.5, "p99_ms": 7.2},
        "overhead_x": 1.1, "parity_vs_offline_loco": 5e-7,
        "parity_rows": 24, "groups": 7,
        "compile_storm": {"max_post_warmup_per_bucket": 0},
        "swap": {"promoted": "v2", "zero_dropped": True,
                 "post_swap_lineage": "v2", "wall_s": 0.1},
    }


def test_explain_overhead_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _explain_overhead_good()
    assert v(good) == []
    assert any("parity" in e for e in v(
        {**good, "parity_vs_offline_loco": 1e-3}))
    assert any("overhead" in e for e in v({**good, "overhead_x": 100.0}))
    assert any("compile-storm" in e for e in v(
        {**good, "compile_storm": {"max_post_warmup_per_bucket": 2}}))
    assert any("groups" in e for e in v({**good, "groups": 1}))
    assert any("rps" in e for e in v(
        {**good, "explained": {"rps": 0, "p50_ms": 1, "p99_ms": 2}}))
    swap = good["swap"]
    assert any("lineage" in e for e in v(
        {**good, "swap": {**swap, "post_swap_lineage": "v1"}}))
    assert any("swap" in e for e in v(
        {**good, "swap": {**swap, "zero_dropped": False}}))
    assert any("swap" in e for e in v(
        {**good, "swap": {**swap, "promoted": ""}}))


def test_explain_overhead_artifact_committed_and_healthy(checker):
    """The round-15 acceptance contract on the COMMITTED artifact:
    explained traffic through the live fleet with parity <= 1e-5 vs the
    offline LOCO path, a bounded measured overhead, ZERO post-warmup
    compiles per (lane, bucket), and explanations surviving the mid-run
    hot-swap with the promoted version's lineage."""
    path = os.path.join(REPO, "benchmarks", "EXPLAIN_OVERHEAD.json")
    assert os.path.exists(path), \
        "benchmarks/EXPLAIN_OVERHEAD.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "explain_overhead"
    assert art["parity_vs_offline_loco"] <= checker.MAX_EXPLAIN_PARITY
    assert art["overhead_x"] <= checker.MAX_EXPLAIN_OVERHEAD_X
    assert art["compile_storm"]["max_post_warmup_per_bucket"] == 0
    assert art["swap"]["zero_dropped"] is True
    assert art["swap"]["post_swap_lineage"] == art["swap"]["promoted"]
    assert art["groups"] >= 2 and art["parity_rows"] > 0
    assert art["ok"] is True


def _wire_speed_good():
    return {
        "metric": "wire_speed", "platform": "cpu",
        "requests": 400, "rows": 51200, "wall_s": 8.0,
        "baseline_fleet_http_rps": 436.2,
        "json": {"rps": 600.0, "p50_ms": 1.4, "p99_ms": 2.9},
        "binary": {"rps": 52000.0, "p50_ms": 1.8, "p99_ms": 3.6,
                   "rows_per_frame": 128,
                   "encode_ms_per_frame": 0.21,
                   "decode_ms_per_frame": 0.34},
        "router": {"json_rps": 520.0, "binary_rps": 41000.0},
        "speedup_vs_json": 86.7, "speedup_vs_baseline": 119.2,
        "parity_vs_json": 3e-8, "parity_rows": 64,
        "compile_storm": {"max_post_warmup_per_bucket": 0},
        "swap": {"promoted": "v2", "zero_dropped": True},
    }


def test_wire_speed_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _wire_speed_good()
    assert v(good) == []
    binary = good["binary"]
    assert any("baseline" in e for e in v(
        {k: x for k, x in good.items()
         if k != "baseline_fleet_http_rps"}))
    assert any("binary leg carries" in e for e in v(
        {**good, "binary": {**binary, "rps": 4000.0}}))
    assert any("p99" in e for e in v(
        {**good, "binary": {**binary, "p99_ms": 9.0}}))
    assert any("rows_per_frame" in e for e in v(
        {**good, "binary": {**binary, "rows_per_frame": 0}}))
    assert any("decode_ms_per_frame" in e for e in v(
        {**good, "binary": {k: x for k, x in binary.items()
                            if k != "decode_ms_per_frame"}}))
    assert any("beat the same-run JSON" in e for e in v(
        {**good, "json": {"rps": 60000.0, "p50_ms": 1.0,
                          "p99_ms": 2.0}}))
    assert any("parity" in e for e in v(
        {**good, "parity_vs_json": 1e-3}))
    assert any("parity_rows" in e for e in v(
        {**good, "parity_rows": 0}))
    assert any("router" in e for e in v(
        {**good, "router": {"json_rps": 520.0, "binary_rps": 0}}))
    assert any("compile-storm" in e for e in v(
        {**good, "compile_storm": {"max_post_warmup_per_bucket": 3}}))
    assert any("swap" in e for e in v(
        {**good, "swap": {"promoted": "v2", "zero_dropped": False}}))
    assert any("swap" in e for e in v(
        {**good, "swap": {"promoted": "", "zero_dropped": True}}))


def test_wire_speed_artifact_committed_and_healthy(checker):
    """The round-16 acceptance contract on the COMMITTED artifact:
    single-replica binary-wire HTTP >= 10x the committed 436 rps
    pre-wire fleet rate with p99 < 5ms, binary-vs-JSON parity <= 1e-5
    through the live server, an encode/decode wall split per frame, a
    through-router passthrough leg, ZERO post-warmup compiles, and zero
    drops through a mid-run hot-swap."""
    path = os.path.join(REPO, "benchmarks", "WIRE_SPEED.json")
    assert os.path.exists(path), \
        "benchmarks/WIRE_SPEED.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "wire_speed"
    assert art["binary"]["rps"] >= (checker.MIN_WIRE_BINARY_SPEEDUP
                                    * art["baseline_fleet_http_rps"])
    assert art["binary"]["rps"] > art["json"]["rps"]
    assert art["binary"]["p99_ms"] <= checker.MAX_WIRE_P99_MS
    assert art["parity_vs_json"] <= checker.MAX_WIRE_PARITY
    assert art["parity_rows"] > 0
    assert art["router"]["binary_rps"] > 0
    assert art["compile_storm"]["max_post_warmup_per_bucket"] == 0
    assert art["swap"]["zero_dropped"] is True


def _multitenant_good():
    return {
        "metric": "multitenant_fleet", "platform": "cpu",
        "requests": 12000, "wall_s": 40.0, "models": 1000,
        "zero_dropped": True, "distinct_models_scored": 180,
        "registration": {"models": 1000, "wall_s": 1.8,
                         "loads_at_register": 0},
        "hot": {"rps": 800.0, "p50_ms": 6.0, "p99_ms": 40.0},
        "cold_start_ms": {"count": 150, "p50": 300.0, "p99": 900.0,
                          "max": 1500.0},
        "fairness": {"baseline_p99_ms": 30.0, "flood_p99_ms": 45.0,
                     "ratio": 1.5, "hot_throttled": 200,
                     "cold_dropped": 0},
        "tiers": {"promotions_disk_ram": 170, "promotions_ram_hbm": 170,
                  "demotions_ram": 110, "demotions_hbm": 80,
                  "ram_budget_bytes": 1 << 26},
    }


def test_multitenant_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _multitenant_good()
    assert v(good) == []
    # the fleet-size floor: the whole claim is "no eager registry
    # could hold this many"
    assert any("models" in e for e in v({**good, "models": 999}))
    assert any("zero_dropped" in e for e in v(
        {**good, "zero_dropped": False}))
    # lazy registration is counter-asserted: ONE np.load at register
    # time breaks the contract
    regn = good["registration"]
    assert any("lazy-registration" in e for e in v(
        {**good, "registration": {**regn, "loads_at_register": 1}}))
    assert any("registration" in e for e in v(
        {k: x for k, x in good.items() if k != "registration"}))
    # the hot-tenant p99 bound while cold tenants page in around it
    assert any("hot-tenant p99" in e for e in v(
        {**good, "hot": {**good["hot"], "p99_ms": 400.0}}))
    # the first-score cold-start SLA
    assert any("cold-start SLA" in e for e in v(
        {**good, "cold_start_ms": {**good["cold_start_ms"],
                                   "p99": 9000.0}}))
    # the fairness experiment: bounded flood damage, flood actually
    # throttled, no cold request dropped
    fair = good["fairness"]
    assert any("fairness bound" in e for e in v(
        {**good, "fairness": {**fair, "ratio": 8.0}}))
    assert any("hot_throttled" in e for e in v(
        {**good, "fairness": {**fair, "hot_throttled": 0}}))
    assert any("cold_dropped" in e for e in v(
        {**good, "fairness": {**fair, "cold_dropped": 3}}))
    # the residency ladder must actually cycle: page-ins AND budget
    # demotions both counted
    tiers = good["tiers"]
    assert any("demotions_ram" in e for e in v(
        {**good, "tiers": {**tiers, "demotions_ram": 0}}))
    assert any("promotions_disk_ram" in e for e in v(
        {**good, "tiers": {**tiers, "promotions_disk_ram": 0}}))
    assert any("ram_budget_bytes" in e for e in v(
        {**good, "tiers": {**tiers, "ram_budget_bytes": 0}}))
    assert any("distinct_models_scored" in e for e in v(
        {k: x for k, x in good.items()
         if k != "distinct_models_scored"}))


def _network_chaos_good():
    return {
        "metric": "network_chaos", "platform": "cpu",
        "requests": 4400, "models": 1000, "wall_s": 30.0,
        "zero_dropped": True, "distinct_requests": 4400,
        "scored_total": 4400, "double_scores": 0,
        "steady": {"rps": 210.0, "p50_ms": 35.0, "p99_ms": 90.0},
        "chaos": {"rps": 205.0, "p50_ms": 36.0, "p99_ms": 110.0},
        "p99_inflation_x": 1.222,
        "faults": {"delay": 10, "reset": 3, "refuse": 2, "split": 12,
                   "truncate": 2, "corrupt": 3, "blackhole": 1},
        "dedupe": {"hits": 5, "waits": 0},
    }


def test_network_chaos_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _network_chaos_good()
    assert v(good) == []
    # the fleet-size floor: chaos against a toy replica proves nothing
    assert any("models" in e for e in v({**good, "models": 999}))
    assert any("zero_dropped" in e for e in v(
        {**good, "zero_dropped": False}))
    # the exactly-once ledger: any double-score is an idempotency hole
    assert any("idempotency" in e for e in v(
        {**good, "double_scores": 1, "scored_total": 4401}))
    # ... and the committed equality must actually add up
    assert any("the equality IS the proof" in e for e in v(
        {**good, "scored_total": 4401}))
    assert any("distinct_requests" in e for e in v(
        {k: x for k, x in good.items() if k != "distinct_requests"}))
    # both legs must carry real latency blocks
    assert any("'steady'" in e for e in v(
        {**good, "steady": {"rps": 0, "p50_ms": 1.0, "p99_ms": 2.0}}))
    assert any("'chaos'" in e for e in v(
        {k: x for k, x in good.items() if k != "chaos"}))
    # the chaos p99 bound, and the inflation must match the legs
    assert any("chaos p99 bound" in e for e in v(
        {**good, "p99_inflation_x": 3.5,
         "chaos": {"rps": 205.0, "p50_ms": 36.0, "p99_ms": 315.0}}))
    assert any("does not match" in e for e in v(
        {**good, "p99_inflation_x": 2.0}))
    # every fault kind must have fired: unfired faults were not survived
    faults = good["faults"]
    assert any("blackhole" in e for e in v(
        {**good, "faults": {k: x for k, x in faults.items()
                            if k != "blackhole"}}))
    assert any("reset" in e for e in v(
        {**good, "faults": {**faults, "reset": 0}}))
    # a retry must actually have been answered from the dedupe ring
    assert any("dedupe.hits" in e for e in v(
        {**good, "dedupe": {"hits": 0, "waits": 0}}))
    assert any("dedupe" in e for e in v(
        {k: x for k, x in good.items() if k != "dedupe"}))


def test_network_chaos_artifact_committed_and_healthy(checker):
    """The round-18 acceptance contract on the COMMITTED artifact: the
    1000-model tenancy fleet scored over the binary wire through a
    deterministic fault proxy on every router -> replica hop, with all
    seven NET fault kinds delivered, zero client-visible drops, the
    exactly-once dedupe equality (sum(scored) == distinct requests,
    double_scores == 0), at least one retry answered from the ring,
    and chaos-leg p99 within the inflation bound of the same-run
    steady leg."""
    path = os.path.join(REPO, "benchmarks", "NETWORK_CHAOS.json")
    assert os.path.exists(path), \
        "benchmarks/NETWORK_CHAOS.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "network_chaos"
    assert art["ok"] is True and art["notes"] == []
    assert art["models"] >= checker.MIN_CHAOS_MODELS
    assert art["zero_dropped"] is True
    assert art["double_scores"] == 0
    assert art["scored_total"] == art["distinct_requests"] > 0
    for kind in checker.REQUIRED_FAULT_KINDS:
        assert art["faults"][kind] >= 1, kind
    assert art["dedupe"]["hits"] >= 1
    assert art["p99_inflation_x"] <= checker.MAX_CHAOS_P99_INFLATION
    assert art["steady"]["rps"] > 0 and art["chaos"]["rps"] > 0
    # provenance: the plan itself is committed so the run is replayable
    assert art["plan"] and isinstance(art["plan_seed"], int)
    assert art["replicas"] >= 2


def test_multitenant_artifact_committed_and_healthy(checker):
    """The round-17 acceptance contract on the COMMITTED artifact:
    >= 1000 model dirs registered lazily (zero checkpoint loads),
    Zipf-skewed traffic with zero drops, the residency ladder cycling
    under a RAM budget, hot-tenant p99 and cold-start p99 within
    bounds, and a hot-tenant flood leaving cold-tenant p99 within the
    fairness ratio."""
    path = os.path.join(REPO, "benchmarks", "MULTITENANT_FLEET.json")
    assert os.path.exists(path), \
        "benchmarks/MULTITENANT_FLEET.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "multitenant_fleet"
    assert art["models"] >= checker.MIN_MT_MODELS
    assert art["zero_dropped"] is True
    assert art["registration"]["loads_at_register"] == 0
    assert art["hot"]["p99_ms"] <= checker.MAX_MT_HOT_P99_MS
    assert art["cold_start_ms"]["p99"] <= checker.MAX_MT_COLD_START_P99_MS
    assert art["fairness"]["ratio"] <= checker.MAX_MT_FAIRNESS_RATIO
    assert art["fairness"]["hot_throttled"] >= 1
    assert art["fairness"]["cold_dropped"] == 0
    assert art["tiers"]["promotions_disk_ram"] >= 1
    assert art["tiers"]["demotions_ram"] >= 1
    assert art["distinct_models_scored"] > 0


def _precision_ladder_good():
    return {
        "metric": "precision_ladder", "platform": "cpu",
        "requests": 1600, "f32_rps": 269.0, "bf16_rps": 281.0,
        "f32": {"rps": 269.0, "p50_ms": 3.6, "p99_ms": 6.7},
        "bf16": {"rps": 281.0, "p50_ms": 3.5, "p99_ms": 6.5},
        "speedup_bf16_x": 1.045,
        "residency": {"budget_bytes": 18256, "per_model_bytes_f32": 4564,
                      "models_resident_f32": 4,
                      "models_resident_bf16": 8, "ratio": 2.0},
        "parity": {"bf16_max_score_diff": 0.006,
                   "int8_max_score_diff": 0.014,
                   "tolerance": 0.05, "rows": 64},
        "gate_rejection": {"rejections": 1, "served_f32": True,
                           "drops": 0, "later_promoted": True},
        "compile_storm": {"max_post_warmup_per_bucket": 0},
        "pressure": {"demotions": 1, "precision_rung_first": True,
                     "buckets_shed_before_demotion": 0},
    }


def test_precision_ladder_artifact_schema_rejections(checker):
    v = checker.validate_artifact
    good = _precision_ladder_good()
    assert v(good) == []
    # both legs must carry real latency blocks
    assert any("'f32'" in e for e in v(
        {k: x for k, x in good.items() if k != "f32"}))
    assert any("'bf16'" in e for e in v(
        {**good, "bf16": {"rps": 0, "p50_ms": 1.0, "p99_ms": 2.0}}))
    # the either-axis rule: slower AND no denser is pure risk
    bad_both = {**good, "speedup_bf16_x": 1.0,
                "residency": {**good["residency"], "ratio": 1.1}}
    assert any("pays on NO axis" in e for e in v(bad_both))
    # ... but ONE passing axis is enough (the CPU residency arm)
    assert v({**good, "speedup_bf16_x": 1.0}) == []
    assert v({**good, "residency": {**good["residency"], "ratio": 1.1},
              "speedup_bf16_x": 1.3}) == []
    # parity beyond the gate tolerance could never have been promoted
    assert any("parity violated" in e for e in v(
        {**good, "parity": {**good["parity"],
                            "int8_max_score_diff": 0.06}}))
    assert any("parity.bf16_max_score_diff" in e for e in v(
        {**good, "parity": {k: x for k, x in good["parity"].items()
                            if k != "bf16_max_score_diff"}}))
    # the gate must have been seen rejecting — and rejecting SAFELY
    assert any("rejections" in e for e in v(
        {**good, "gate_rejection": {**good["gate_rejection"],
                                    "rejections": 0}}))
    assert any("served_f32" in e for e in v(
        {**good, "gate_rejection": {**good["gate_rejection"],
                                    "served_f32": False}}))
    assert any("drops" in e for e in v(
        {**good, "gate_rejection": {**good["gate_rejection"],
                                    "drops": 1}}))
    assert any("later_promoted" in e for e in v(
        {**good, "gate_rejection": {**good["gate_rejection"],
                                    "later_promoted": False}}))
    # steady state must be compile-free per (bucket, rung)
    assert any("compile_storm" in e for e in v(
        {**good, "compile_storm": {"max_post_warmup_per_bucket": 1}}))
    # pressure must take the precision rung BEFORE bucket shedding
    assert any("precision_rung_first" in e for e in v(
        {**good, "pressure": {**good["pressure"],
                              "precision_rung_first": False}}))
    assert any("buckets_shed_before_demotion" in e for e in v(
        {**good, "pressure": {**good["pressure"],
                              "buckets_shed_before_demotion": 1}}))


def test_precision_ladder_artifact_committed_and_healthy(checker):
    """The round-20 acceptance contract on the COMMITTED artifact: the
    bf16 rung pays on at least one axis (speed or HBM residency), both
    promoted rungs hold parity within the gate tolerance, the gate was
    observed rejecting while serving f32 with zero drops, steady-state
    traffic never compiled, and the pressure path demoted precision
    before shedding a bucket."""
    path = os.path.join(REPO, "benchmarks", "PRECISION_LADDER.json")
    assert os.path.exists(path), \
        "benchmarks/PRECISION_LADDER.json not committed"
    art = json.load(open(path))
    assert checker.validate_artifact(art) == []
    assert art["metric"] == "precision_ladder"
    assert (art["speedup_bf16_x"] >= checker.MIN_BF16_SPEEDUP
            or art["residency"]["ratio"]
            >= checker.MIN_PRECISION_RESIDENCY_RATIO)
    tol = art["parity"]["tolerance"]
    assert art["parity"]["bf16_max_score_diff"] <= tol
    assert art["parity"]["int8_max_score_diff"] <= tol
    assert art["gate_rejection"]["rejections"] >= 1
    assert art["gate_rejection"]["served_f32"] is True
    assert art["gate_rejection"]["drops"] == 0
    assert art["gate_rejection"]["later_promoted"] is True
    assert art["compile_storm"]["max_post_warmup_per_bucket"] == 0
    assert art["pressure"]["precision_rung_first"] is True
    assert art["pressure"]["buckets_shed_before_demotion"] == 0
    assert art["pressure"]["demotions"] >= 1
    # counted residency, not arithmetic: the cache really held 2x models
    assert art["residency"]["models_resident_bf16"] \
        >= art["residency"]["models_resident_f32"]
