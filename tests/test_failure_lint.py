"""``scripts/check_failure_paths.py`` wired into tier-1: every broad
``except`` in ``transmogrifai_tpu/`` must re-raise, warn, or carry an
explicit ``failure-ok``/``noqa`` acknowledgement — silent fault swallowing
in the framework fails CI loudly."""

import importlib.util
import os
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name.replace(".py", "").replace("/", "_"), os.path.join(REPO, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _linter():
    return _load_script("scripts/check_failure_paths.py")


def _check_src(tmp_path, src):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    return _linter().check_file(str(p))


def test_package_has_no_silent_failure_paths():
    lint = _linter()
    violations = lint.check_tree(os.path.join(REPO, "transmogrifai_tpu"))
    assert violations == [], "\n".join(violations)


def test_flags_silent_broad_except(tmp_path):
    out = _check_src(tmp_path, """
        try:
            x = 1
        except Exception:
            pass
    """)
    assert len(out) == 1 and "swallows" in out[0]


def test_flags_bare_except_and_tuple(tmp_path):
    out = _check_src(tmp_path, """
        try:
            x = 1
        except:
            x = 2
        try:
            x = 3
        except (ValueError, Exception):
            x = 4
    """)
    assert len(out) == 2


def test_accepts_reraise_warn_marker_and_narrow(tmp_path):
    out = _check_src(tmp_path, """
        import warnings
        try:
            x = 1
        except Exception:
            raise
        try:
            x = 2
        except Exception as e:
            warnings.warn(str(e))
        try:
            x = 3
        except Exception:  # failure-ok: optional probe
            pass
        try:
            x = 4
        except ValueError:
            pass
        try:
            x = 5
        except Exception as e:  # noqa: BLE001 — filtered below
            x = 6
    """)
    assert out == []


def test_bare_noqa_without_reason_is_not_an_escape_hatch(tmp_path):
    out = _check_src(tmp_path, """
        try:
            x = 1
        except Exception:  # noqa: E501
            pass
    """)
    assert len(out) == 1


def test_cli_exit_codes(tmp_path):
    lint = _linter()
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "a.py").write_text("x = 1\n")
    assert lint.main([str(clean)]) == 0
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "b.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n")
    assert lint.main([str(dirty)]) == 1
