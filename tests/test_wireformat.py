"""Binary columnar scoring wire (``serving/wireformat.py`` +
the live-server frame lane): property-style codec round trips across
every dtype (nulls, unicode, empty batches), corrupt/truncated-frame
rejection with clean 400s, JSON-vs-binary parity through a LIVE HTTP
server, and the NDJSON compat lane — ONE shared module-scoped model."""

import http.client
import json

import numpy as np
import pytest

from transmogrifai_tpu.serving import wireformat as wf

# -- pure codec (no model, no jax) -------------------------------------------

_ALL_DTYPES = (wf.F64, wf.F32, wf.I64, wf.I32, wf.BOOL, wf.TEXT,
               wf.JSONCOL)

#: unicode corpus: multibyte, astral-plane, RTL, combining, empty
_TEXTS = ["", "plain", "héllo wörld", "日本語のテキスト", "🚀🧪💡",
          "مرحبا بالعالم", "éclair", "tab\tand\nnewline",
          "null\x00byte"]


def _random_column(rng, name, n):
    """One random WireColumn of a random dtype (possibly masked)."""
    dtype = _ALL_DTYPES[int(rng.integers(len(_ALL_DTYPES)))]
    masked = bool(rng.integers(2)) and n > 0
    mask = None
    if masked:
        mask = rng.integers(0, 2, size=n).astype(bool)
        if n:
            mask[int(rng.integers(n))] = True  # keep >= 1 present
    if dtype == wf.F64:
        vals = rng.normal(size=n).astype(np.float64)
    elif dtype == wf.F32:
        vals = rng.normal(size=n).astype(np.float32)
    elif dtype == wf.I64:
        vals = rng.integers(-2**40, 2**40, size=n).astype(np.int64)
    elif dtype == wf.I32:
        vals = rng.integers(-2**20, 2**20, size=n).astype(np.int32)
    elif dtype == wf.BOOL:
        vals = rng.integers(0, 2, size=n).astype(np.uint8)
    elif dtype == wf.TEXT:
        vals = [None if (mask is not None and not mask[i])
                else _TEXTS[int(rng.integers(len(_TEXTS)))]
                for i in range(n)]
        mask = None  # text nulls ride the values, not the bitmap
    else:  # JSONCOL: arbitrary nested python values
        pool = [None, 1, 2.5, True, "s", {"a": [1, 2]}, ["x", {"y": 3}],
                {"uni": "héllo"}]
        vals = [pool[int(rng.integers(len(pool)))] for _ in range(n)]
        mask = None
    return wf.WireColumn(name, dtype, vals, mask)


def _assert_column_equal(sent: wf.WireColumn, got: wf.WireColumn, n):
    assert got.dtype == sent.dtype
    if sent.dtype in (wf.TEXT, wf.JSONCOL):
        assert list(got.values) == list(sent.values)
        return
    sent_mask = sent.mask if sent.mask is not None \
        else np.ones(n, bool)
    got_mask = got.mask if got.mask is not None else np.ones(n, bool)
    assert np.array_equal(sent_mask, got_mask)
    sv = np.asarray(sent.values)[sent_mask]
    gv = np.asarray(got.values)[got_mask]
    assert gv.dtype == sv.dtype
    assert np.array_equal(sv, gv)


def test_roundtrip_random_schemas():
    """Property-style: 30 random (schema, batch) pairs — every dtype,
    random null bitmaps, unicode text, zero-row and zero-column frames
    — survive encode -> decode exactly."""
    rng = np.random.default_rng(11)
    for trial in range(30):
        n = int(rng.integers(0, 41))
        n_cols = int(rng.integers(0, 7))
        cols = [_random_column(rng, f"c{j}_é", n)
                for j in range(n_cols)]
        meta = {"trial": trial, "uni": "méta"} \
            if rng.integers(2) else None
        buf = wf.encode_frame(f"model-{trial}-ü", cols, n,
                              meta=meta)
        assert wf.peek_model_id(buf) == f"model-{trial}-ü"
        frame = wf.decode_frame(buf)
        assert frame.kind == wf.KIND_REQUEST
        assert frame.n_rows == n
        assert frame.meta == (meta or {})
        assert list(frame.columns) == [c.name for c in cols]
        for c in cols:
            _assert_column_equal(c, frame.columns[c.name], n)


def test_roundtrip_empty_batch_and_empty_frame():
    buf = wf.encode_frame("m", [], 0)
    frame = wf.decode_frame(buf)
    assert frame.n_rows == 0 and frame.columns == {}
    # zero rows but a declared schema
    cols = [wf.WireColumn("x", wf.F64, np.zeros(0)),
            wf.WireColumn("t", wf.TEXT, [])]
    frame = wf.decode_frame(wf.encode_frame("m", cols, 0))
    assert frame.n_rows == 0
    assert list(frame.columns) == ["x", "t"]


def test_rows_to_columns_roundtrip_rows():
    rows = [{"x": 1.5, "b": True, "s": "héllo", "j": {"k": [1]}},
            {"x": None, "b": False, "s": None, "j": None},
            {"x": -2.0, "b": None, "s": "🚀", "j": [3, 4]}]
    frame = wf.decode_frame(wf.encode_rows("m", rows))
    assert wf.frame_to_rows(frame) == rows


def test_reply_roundtrip_dotted_names():
    cols = wf.reply_columns(
        {"pred.prediction": np.array([1.0, 0.0]),
         "pred.probability_0": np.array([0.25, 0.75], np.float64),
         "plain": [{"a": 1}, None]}, 2)
    frame = wf.decode_frame(
        wf.encode_frame("m", cols, 2, kind=wf.KIND_REPLY))
    rows = wf.reply_to_rows(frame)
    assert rows[0]["pred"] == {"prediction": 1.0, "probability_0": 0.25}
    assert rows[1]["plain"] is None


def test_truncated_frames_rejected():
    """Every proper prefix of a valid frame is a clean
    ``WireFormatError`` — never an IndexError/struct.error crash."""
    cols = [wf.WireColumn("x", wf.F64, np.arange(5.0)),
            wf.WireColumn("t", wf.TEXT, list("abcde"))]
    buf = wf.encode_frame("model-1", cols, 5)
    step = max(len(buf) // 64, 1)  # sample prefixes, always incl. 0
    for cut in list(range(0, len(buf), step)) + [len(buf) - 1]:
        with pytest.raises(wf.WireFormatError):
            wf.decode_frame(buf[:cut])
    with pytest.raises(wf.WireFormatError):
        wf.peek_model_id(buf[:wf.MODEL_ID_OFFSET + 2])


def test_corrupt_frames_rejected():
    cols = [wf.WireColumn("x", wf.F64, np.arange(4.0))]
    good = bytearray(wf.encode_frame("m", cols, 4))
    bad_magic = bytearray(good)
    bad_magic[4:8] = b"NOPE"
    with pytest.raises(wf.WireFormatError, match="magic"):
        wf.decode_frame(bytes(bad_magic))
    bad_version = bytearray(good)
    bad_version[8] = 99
    with pytest.raises(wf.WireFormatError, match="version"):
        wf.decode_frame(bytes(bad_version))
    bad_kind = bytearray(good)
    bad_kind[9] = 77
    with pytest.raises(wf.WireFormatError):
        wf.decode_frame(bytes(bad_kind))
    # frame_len lying about the payload size
    lies = bytearray(good)
    lies[0:4] = (len(good) * 3).to_bytes(4, "little")
    with pytest.raises(wf.WireFormatError):
        wf.decode_frame(bytes(lies))
    # oversize declaration: refused before any allocation
    huge = bytearray(good)
    huge[0:4] = (wf.MAX_FRAME_BYTES + 1).to_bytes(4, "little")
    with pytest.raises(wf.WireFormatError):
        wf.decode_frame(bytes(huge))


def test_random_garbage_rejected():
    rng = np.random.default_rng(5)
    for _ in range(50):
        blob = rng.integers(0, 256,
                            size=int(rng.integers(0, 200))).astype(
                                np.uint8).tobytes()
        with pytest.raises(wf.WireFormatError):
            wf.decode_frame(blob)


def test_text_offsets_must_be_monotonic():
    cols = [wf.WireColumn("t", wf.TEXT, ["aa", "bb", "cc"])]
    buf = bytearray(wf.encode_frame("m", cols, 3))
    # the offsets vector is the first 8-byte-aligned buffer after the
    # column table; flip one offset pair to be decreasing
    base = buf.rfind(b"aabbcc") - 4 * 4
    buf[base + 4:base + 8] = (6).to_bytes(4, "little")
    with pytest.raises(wf.WireFormatError):
        wf.decode_frame(bytes(buf))


# -- live server (ONE shared module-scoped model) ----------------------------

@pytest.fixture(scope="module")
def served():
    """One model, one running fleet HTTP endpoint for every live-wire
    test in this module."""
    from test_serving import _make_model
    from transmogrifai_tpu.serving import FleetServer
    model, rows = _make_model()
    fleet = FleetServer(max_batch=16, max_wait_ms=1.0, metrics_port=0)
    fleet.register(model=model, model_id="m1")
    fleet.start()
    try:
        yield {"fleet": fleet, "model": model, "rows": rows,
               "port": fleet.metrics_http.port}
    finally:
        fleet.stop()


def _conn(served):
    return http.client.HTTPConnection("127.0.0.1", served["port"],
                                      timeout=30)


def _post(conn, path, body, ctype="application/json"):
    conn.request("POST", path, body, {"Content-Type": ctype})
    resp = conn.getresponse()
    return resp.status, resp.getheader("Content-Type"), resp.read()


def test_live_json_vs_binary_parity(served):
    """The same 24 rows through the JSON wire (one POST per row) and
    the binary frame wire (one POST total) agree to 1e-9 on every
    score field, and the framed reply carries trace + lineage meta."""
    rows = served["rows"][:24]
    conn = _conn(served)
    json_docs = []
    for r in rows:
        status, ctype, body = _post(conn, "/score/m1", json.dumps(r))
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        doc.pop("traceId"), doc.pop("lineage")
        json_docs.append(doc)
    status, ctype, body = _post(conn, "/score/m1",
                                wf.encode_rows("m1", rows),
                                ctype=wf.CONTENT_TYPE_FRAME)
    assert status == 200
    assert ctype == wf.CONTENT_TYPE_FRAME
    reply = wf.decode_frame(body)
    assert reply.kind == wf.KIND_REPLY
    assert reply.meta["lineage"]["modelId"] == "m1"
    frame_docs = wf.reply_to_rows(reply)
    assert len(frame_docs) == len(json_docs)
    from test_serving import _diff
    worst = max(_diff(a, b) for a, b in zip(json_docs, frame_docs))
    assert worst <= 1e-9, worst
    conn.close()


def test_live_model_id_from_frame_header(served):
    """POST /score with no path id: the frame header's model id
    routes."""
    conn = _conn(served)
    status, ctype, body = _post(conn, "/score",
                                wf.encode_rows("m1", served["rows"][:3]),
                                ctype=wf.CONTENT_TYPE_FRAME)
    assert status == 200 and ctype == wf.CONTENT_TYPE_FRAME
    assert wf.decode_frame(body).n_rows == 3
    conn.close()


def test_live_corrupt_frame_400_connection_survives(served):
    """Truncated and garbage frames answer 400 with a JSON error body —
    and the keep-alive connection keeps serving afterwards."""
    good = wf.encode_rows("m1", served["rows"][:4])
    conn = _conn(served)
    for bad in (good[: len(good) // 2], b"\x00" * 40, b""):
        status, ctype, body = _post(conn, "/score/m1", bad,
                                    ctype=wf.CONTENT_TYPE_FRAME)
        assert status == 400, (bad[:16], status, body)
        assert ctype == "application/json"
        doc = json.loads(body)
        assert "error" in doc and doc["traceId"]
    # same socket still scores
    status, ctype, body = _post(conn, "/score/m1", good,
                                ctype=wf.CONTENT_TYPE_FRAME)
    assert status == 200
    conn.close()


def test_live_unknown_model_frame_404(served):
    conn = _conn(served)
    status, _, body = _post(conn, "/score/ghost",
                            wf.encode_rows("ghost", served["rows"][:2]),
                            ctype=wf.CONTENT_TYPE_FRAME)
    assert status == 404
    assert "error" in json.loads(body)
    conn.close()


def test_live_empty_frame(served):
    conn = _conn(served)
    status, ctype, body = _post(conn, "/score/m1",
                                wf.encode_frame("m1", [], 0),
                                ctype=wf.CONTENT_TYPE_FRAME)
    assert status == 200 and ctype == wf.CONTENT_TYPE_FRAME
    assert wf.decode_frame(body).n_rows == 0
    conn.close()


def test_live_ndjson_compat(served):
    """NDJSON stays served on the same port: one doc per line, same
    order, a poison middle line answers INLINE without voiding the
    batch."""
    rows = served["rows"][:5]
    lines = [json.dumps(r) for r in rows]
    lines[2] = "{not json"
    conn = _conn(served)
    status, ctype, body = _post(conn, "/score/m1",
                                "\n".join(lines) + "\n",
                                ctype="application/x-ndjson")
    assert status == 200
    assert ctype == "application/x-ndjson"
    docs = [json.loads(ln) for ln in body.splitlines() if ln.strip()]
    assert len(docs) == 5
    for i, d in enumerate(docs):
        if i == 2:
            assert "error" in d
        else:
            assert "error" not in d and "prediction" in str(d)
    conn.close()


def test_live_json_lane_unchanged(served):
    """Plain JSON clients are untouched by the wire work: default
    content type still scores one row -> one document."""
    conn = _conn(served)
    status, ctype, body = _post(conn, "/score/m1",
                                json.dumps(served["rows"][0]))
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert doc["lineage"]["modelId"] == "m1" and doc["traceId"]
    conn.close()


def test_wire_json_pins_endpoint_json_only(served):
    """``wire="json"`` (the CLI's --wire json) disables frame
    negotiation: frame POSTs answer 400, JSON keeps working."""
    from transmogrifai_tpu.serving import FleetServer
    fleet = FleetServer(max_batch=16, max_wait_ms=1.0, metrics_port=0,
                        wire="json")
    fleet.register(model=served["model"], model_id="m1")
    fleet.start()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", fleet.metrics_http.port, timeout=30)
        status, _, body = _post(conn, "/score/m1",
                                wf.encode_rows("m1", served["rows"][:2]),
                                ctype=wf.CONTENT_TYPE_FRAME)
        assert status == 400
        assert "unsupported" in json.loads(body)["error"]
        status, _, body = _post(conn, "/score/m1",
                                json.dumps(served["rows"][0]))
        assert status == 200
        conn.close()
    finally:
        fleet.stop()
