"""Fold x grid-stacked TREE sweep (round 8): exact stacked-vs-loop metric
parity for RF/GBT on binary and regression suites, the one-sync-per-
depth-group counter contract, HBM-guard lane chunking, checkpoint resume
across layouts (stacked <-> loop), gating overrides, the batched
histogram engines, and the capability rules."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.models.base import (
    supports_fold_stacking, supports_tree_stacking,
)
from transmogrifai_tpu.models.linear import OpLinearSVC
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier, OpGBTClassifier, OpGBTRegressor,
    OpRandomForestClassifier, OpRandomForestRegressor, OpXGBoostClassifier,
)
from transmogrifai_tpu.selector import (
    BinaryClassificationModelSelector, DataSplitter, RegressionModelSelector,
)
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.uid import UID
from transmogrifai_tpu.utils.profiling import sweep_counters
from transmogrifai_tpu.workflow import Workflow


def _frame(n=240, seed=0, regression=False, classes=2):
    rng = np.random.default_rng(seed)
    if regression:
        x = rng.normal(size=n)
        y = 2.0 * x + rng.normal(size=n) * 0.3
    else:
        y = rng.integers(0, classes, n).astype(float)
        x = rng.normal(size=n) + 0.8 * y
    return fr.HostFrame.from_dict({
        "x": (ft.Real, x.tolist()),
        "x2": (ft.Real, rng.normal(size=n).tolist()),
        "label": (ft.RealNN, y.tolist()),
    })


def _train(selector, frame):
    UID.reset()
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    vec = transmogrify(list(feats.values()), min_support=1)
    pred = label.transform_with(selector, vec)
    return (Workflow().set_input_frame(frame)
            .set_result_features(pred).train())


def _tree_binary_selector(**kw):
    """Same-shape lanes per family: every lane of a family shares one
    compiled-program shape, so stacked-vs-loop parity is EXACT (both
    paths score through the binned batch metric)."""
    return BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, seed=1,
        models_and_parameters=[
            (OpGBTClassifier(num_rounds=3, max_depth=2, max_bins=8),
             [{"learning_rate": lr} for lr in (0.1, 0.3)]),
            (OpRandomForestClassifier(num_rounds=3, max_depth=2,
                                      max_bins=8),
             [{"reg_lambda": rl} for rl in (1e-3, 1e-2)]),
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1), **kw)


def _tree_regression_selector(**kw):
    return RegressionModelSelector.with_cross_validation(
        n_folds=2, seed=1,
        models_and_parameters=[
            (OpGBTRegressor(num_rounds=3, max_depth=2, max_bins=8),
             [{"learning_rate": lr} for lr in (0.1, 0.3)]),
            (OpRandomForestRegressor(num_rounds=3, max_depth=2, max_bins=8),
             [{"reg_lambda": rl} for rl in (1e-3, 1e-2)]),
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1), **kw)


@pytest.fixture(scope="module")
def shared_frame():
    """ONE 240-row binary frame shared by every test that exercises the
    canonical ``_tree_binary_selector`` (tier-1 wall: training the same
    selector on per-test frames re-paid the full sweep repeatedly)."""
    return _frame()


@pytest.fixture(scope="module")
def stacked_run(shared_frame):
    """Module-scoped canonical STACKED sweep: (summary, counters) for
    ``_tree_binary_selector`` trained once with stacking forced on."""
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
        sweep_counters.reset()
        s = _train(_tree_binary_selector(), shared_frame).selector_summary()
        return s, sweep_counters.to_json()


@pytest.fixture(scope="module")
def loop_run(shared_frame):
    """Module-scoped canonical per-fold LOOP sweep on the same frame."""
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
        sweep_counters.reset()
        s = _train(_tree_binary_selector(), shared_frame).selector_summary()
        return s, sweep_counters.to_json()


def _summaries_equal(s1, s2, tol=1e-6):
    assert s1.best_model_name == s2.best_model_name
    v1 = {r.model_name: r.metric_values for r in s1.validation_results}
    v2 = {r.model_name: r.metric_values for r in s2.validation_results}
    assert set(v1) == set(v2)
    for k in v1:
        for m in v1[k]:
            assert abs(v1[k][m] - v2[k][m]) <= tol, (k, m)


def test_tree_stacked_parity_binary(stacked_run, loop_run):
    """RF + GBT: the fold x grid-stacked path reproduces the per-fold
    loop's winner and per-candidate metrics EXACTLY (same binned sweep
    metric, same bin-once codes, same PRNG draws)."""
    s1, c1 = stacked_run
    s2, c2 = loop_run
    _summaries_equal(s1, s2, tol=0.0)
    assert all(v["mode"] == "tree_stacked" for v in c1.values()), c1
    assert all(v["mode"] == "fold_loop" for v in c2.values()), c2


def test_tree_stacked_parity_regression(monkeypatch):
    frame = _frame(seed=3, regression=True)
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    s1 = _train(_tree_regression_selector(), frame).selector_summary()
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    s2 = _train(_tree_regression_selector(), frame).selector_summary()
    _summaries_equal(s1, s2, tol=0.0)


def test_tree_stacked_one_sync_per_depth_group(monkeypatch):
    """The acceptance counter: a tree depth-group costs <= 1 blocking
    host sync and 1 fused dispatch for all k folds x L lanes. A
    mixed-depth grid forms one group per depth; each costs one
    dispatch + one sync (the loop pays k dispatches and, for mixed
    shapes with no batched scorer, k x L syncs)."""
    frame = _frame(seed=5)
    sel = lambda: BinaryClassificationModelSelector.with_cross_validation(  # noqa: E731
        n_folds=3, seed=1,
        models_and_parameters=[
            (OpGBTClassifier(num_rounds=3, max_depth=2, max_bins=8),
             [{"learning_rate": lr} for lr in (0.1, 0.3)]),   # 1 group
            (OpRandomForestClassifier(num_rounds=3, max_depth=2,
                                      max_bins=8),
             [{"max_depth": 2}, {"max_depth": 3}]),           # 2 groups
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1))
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    sweep_counters.reset()
    _train(sel(), frame)
    c = sweep_counters.to_json()
    gbt, rf = c["OpGBTClassifier_0"], c["OpRandomForestClassifier_1"]
    assert gbt["mode"] == rf["mode"] == "tree_stacked"
    assert gbt["stackedGroups"] == 1 and rf["stackedGroups"] == 2
    # <= 1 sync and 1 dispatch PER GROUP (no chunking at default budget)
    assert gbt["hostSyncs"] == gbt["deviceDispatches"] == 1, gbt
    assert rf["hostSyncs"] == rf["deviceDispatches"] == 2, rf
    assert gbt["laneChunks"] == 1 and rf["laneChunks"] == 2
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    sweep_counters.reset()
    _train(sel(), frame)
    c = sweep_counters.to_json()
    assert c["OpGBTClassifier_0"]["hostSyncs"] == 3       # one per fold
    assert c["OpRandomForestClassifier_1"]["hostSyncs"] == 6  # k x L


def test_tree_stacked_mixed_depth_close_to_loop(monkeypatch):
    """Mixed-depth grids: the loop path has no batched scorer (mixed
    shapes) and falls to the EXACT per-model metric, while the stacked
    path scores through the binned batch metric — the same binned-vs-
    exact estimator gap the linear sweep already carries. Values agree
    to the binned-metric resolution."""
    frame = _frame(seed=6)
    sel = lambda: BinaryClassificationModelSelector.with_cross_validation(  # noqa: E731
        n_folds=2, seed=1,
        models_and_parameters=[
            (OpRandomForestClassifier(num_rounds=3, max_depth=2,
                                      max_bins=8),
             [{"max_depth": 2}, {"max_depth": 3}]),
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1))
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    s1 = _train(sel(), frame).selector_summary()
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    s2 = _train(sel(), frame).selector_summary()
    v1 = {r.model_name: r.metric_values for r in s1.validation_results}
    v2 = {r.model_name: r.metric_values for r in s2.validation_results}
    assert set(v1) == set(v2)
    for k in v1:
        for m in v1[k]:
            assert abs(v1[k][m] - v2[k][m]) <= 5e-3, (k, m)


def test_tree_stacking_capability_rules():
    assert supports_tree_stacking(OpGBTClassifier())
    assert supports_tree_stacking(OpGBTRegressor())
    assert supports_tree_stacking(OpXGBoostClassifier())
    assert supports_tree_stacking(OpRandomForestClassifier())
    assert supports_tree_stacking(OpRandomForestRegressor())
    # decision trees mutate bootstrap inside a custom fit_arrays below the
    # opt-in: their semantics must keep running in the loop
    assert not supports_tree_stacking(OpDecisionTreeClassifier())
    # non-tree families never opt into the TREE contract (and trees never
    # opt into the linear fold-stacking one)
    assert not supports_tree_stacking(OpLinearSVC())
    assert not supports_fold_stacking(OpGBTClassifier())

    class CountingGBT(OpGBTClassifier):
        def grid_fit_arrays(self, X, y, w, grid, **kw):
            return super().grid_fit_arrays(X, y, w, grid, **kw)

    assert not supports_tree_stacking(CountingGBT())


def test_tree_stacked_default_gating(monkeypatch):
    """Plain CPU defaults to the loop (the microbench artifact gates the
    flip); TRANSMOGRIFAI_TREE_STACKED forces either way."""
    from transmogrifai_tpu.selector.model_selector import ModelSelector
    monkeypatch.delenv("TRANSMOGRIFAI_TREE_STACKED", raising=False)
    expected_default = jax.default_backend() != "cpu"
    assert ModelSelector._tree_stacked_enabled() == expected_default
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    assert ModelSelector._tree_stacked_enabled()
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    assert not ModelSelector._tree_stacked_enabled()
    monkeypatch.delenv("TRANSMOGRIFAI_TREE_STACKED")
    from transmogrifai_tpu.parallel.mesh import make_mesh, use_mesh
    with use_mesh(make_mesh()):
        assert ModelSelector._tree_stacked_enabled()  # meshes default ON


def test_tree_stacked_multiclass_falls_back(monkeypatch):
    """Multiclass has no scalar stacked score: the family keeps the
    per-fold loop even with stacking forced on."""
    frame = _frame(seed=7, classes=3)
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    sweep_counters.reset()
    sel = BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, seed=1,
        models_and_parameters=[
            (OpRandomForestClassifier(num_rounds=2, max_depth=2,
                                      max_bins=8), [{}]),
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1))
    _train(sel, frame)
    c = sweep_counters.to_json()
    assert c["OpRandomForestClassifier_0"]["mode"] == "fold_loop", c


def test_tree_stacked_bin_once_disabled_falls_back(monkeypatch):
    """TRANSMOGRIFAI_TREE_BIN_ONCE=0 requests exact per-fold quantile
    edges — nothing stacks, the loop keeps the family, results match the
    loop run bit for bit."""
    frame = _frame(seed=8)
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_BIN_ONCE", "0")
    sweep_counters.reset()
    s1 = _train(_tree_binary_selector(), frame).selector_summary()
    assert all(v["mode"] == "fold_loop"
               for v in sweep_counters.to_json().values())
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    s2 = _train(_tree_binary_selector(), frame).selector_summary()
    _summaries_equal(s1, s2, tol=0.0)


def test_hbm_guard_lane_chunking(monkeypatch, shared_frame, stacked_run):
    """A budget that fits one lane but not two splits each depth-group
    into lane chunks — one dispatch + one sync per chunk, metrics
    identical to the unchunked run (the shared module-scoped stacked
    sweep); an impossible budget (not even one lane) drops the family
    all the way to the loop."""
    frame = shared_frame
    est = OpGBTClassifier(num_rounds=3, max_depth=2, max_bins=8)
    group = est.tree_stack_groups(
        [{"learning_rate": 0.1}, {"learning_rate": 0.3}])[0]
    # the training frame: 240 rows, 0.2 holdout -> 192; 3 folds -> 128
    # training rows / 64 validation rows; 2 transmogrified features
    shared, per_lane = est.tree_stack_bytes(3, 128, 64, 2, group)
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    monkeypatch.setenv("TRANSMOGRIFAI_SWEEP_HBM_BUDGET",
                       str(shared + 1.5 * per_lane))
    sweep_counters.reset()
    s1 = _train(_tree_binary_selector(), frame).selector_summary()
    c = sweep_counters.to_json()
    for name, fc in c.items():
        assert fc["mode"] == "tree_stacked", (name, fc)
        assert fc["stackedGroups"] == 1, (name, fc)
        assert fc["laneChunks"] == 2, (name, fc)       # 2 lanes, 1 each
        assert fc["hostSyncs"] == 2, (name, fc)        # one per chunk
    monkeypatch.delenv("TRANSMOGRIFAI_SWEEP_HBM_BUDGET")
    _summaries_equal(s1, stacked_run[0], tol=0.0)
    # not even one lane: the whole family keeps the per-fold loop
    monkeypatch.setenv("TRANSMOGRIFAI_SWEEP_HBM_BUDGET", "1")
    sweep_counters.reset()
    s3 = _train(_tree_binary_selector(), frame).selector_summary()
    assert all(v["mode"] == "fold_loop"
               for v in sweep_counters.to_json().values())
    _summaries_equal(s1, s3, tol=0.0)


class CrashOnce(OpLinearSVC):
    """Simulates a mid-sweep crash (NOT an isolated candidate failure):
    KeyboardInterrupt escapes the per-family isolation by design."""
    crash = {"on": True}

    def grid_fit_arrays(self, X, y, w, grid):
        if type(self).crash["on"]:
            raise KeyboardInterrupt("simulated mid-sweep crash")
        return super().grid_fit_arrays(X, y, w, grid)


def _crash_selector(ckpt, stacked_tree_first=True):
    return BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, seed=1,
        models_and_parameters=[
            (OpGBTClassifier(num_rounds=3, max_depth=2, max_bins=8),
             [{"learning_rate": lr} for lr in (0.1, 0.3)]),
            (CrashOnce(max_iter=25), [{"reg_param": 0.01}]),
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1),
        checkpoint_dir=ckpt)


def test_checkpoint_stacked_written_loop_resumed(tmp_path, monkeypatch):
    """A crash after the tree family completes on the STACKED path leaves
    per-group treestack keys; a re-run under the LOOP layout replays them
    without refitting (and vice versa below)."""
    frame = _frame(seed=10)
    ckpt = str(tmp_path / "sweep")
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    CrashOnce.crash["on"] = True
    with pytest.raises(KeyboardInterrupt):
        _train(_crash_selector(ckpt), frame)
    saved = json.load(open(os.path.join(ckpt, "sweep.json")))
    keys = sorted(saved["entries"])
    # {ci}:treestack:{gi}:{k}x{n_tr}x{d}:{L}x{depth} — shape-keyed like
    # the per-fold and linear stacked keys (reshaped data must recompute)
    assert len(keys) == 1 and keys[0].startswith("0:treestack:0:3x") \
        and keys[0].endswith(":2x2"), keys
    assert len(saved["entries"][keys[0]]) == 3 * 2  # fold-major k x L

    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    CrashOnce.crash["on"] = False
    sel = _crash_selector(ckpt)
    gbt = sel.models_and_grids[0][0]
    calls = {"n": 0}
    orig = gbt.grid_fit_arrays

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)
    gbt.grid_fit_arrays = counting
    model = _train(sel, frame)
    assert calls["n"] == 0  # replayed from the treestack checkpoint
    names = {r.model_name
             for r in model.selector_summary().validation_results}
    assert any(n.startswith("OpGBTClassifier_0") for n in names)
    assert any(n.startswith("CrashOnce_1") for n in names)


def test_checkpoint_loop_written_stacked_resumed(tmp_path, monkeypatch):
    """The reverse layout hop: per-fold keys written by the loop path
    replay under the stacked path without retraining."""
    frame = _frame(seed=11)
    ckpt = str(tmp_path / "sweep")
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "0")
    CrashOnce.crash["on"] = True
    with pytest.raises(KeyboardInterrupt):
        _train(_crash_selector(ckpt), frame)
    saved = json.load(open(os.path.join(ckpt, "sweep.json")))
    assert all(":treestack:" not in k for k in saved["entries"])
    assert len(saved["entries"]) == 3  # one per (fold, tree family)

    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")
    CrashOnce.crash["on"] = False
    sel = _crash_selector(ckpt)
    gbt = sel.models_and_grids[0][0]
    calls = {"n": 0}
    orig = gbt.tree_stack_scores

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)
    gbt.tree_stack_scores = counting
    model = _train(sel, frame)
    assert calls["n"] == 0  # replayed from the per-fold checkpoint
    sweep_counters.reset()


def test_checkpoint_mid_family_group_resume(tmp_path, monkeypatch):
    """A crash BETWEEN depth-groups of one family: the completed group's
    treestack key replays, only the remaining group dispatches."""
    frame = _frame(seed=12)
    ckpt = str(tmp_path / "sweep")
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_STACKED", "1")

    def make_sel():
        return BinaryClassificationModelSelector.with_cross_validation(
            n_folds=2, seed=1,
            models_and_parameters=[
                (OpRandomForestClassifier(num_rounds=2, max_depth=2,
                                          max_bins=8),
                 [{"max_depth": 2}, {"max_depth": 3}]),  # 2 depth-groups
            ],
            splitter=DataSplitter(reserve_test_fraction=0.2, seed=1),
            checkpoint_dir=ckpt)

    sel = make_sel()
    rf = sel.models_and_grids[0][0]
    calls = {"n": 0}
    orig = rf.tree_stack_scores

    def crash_second(*a, **k):
        calls["n"] += 1
        if calls["n"] == 2:
            raise KeyboardInterrupt("crash between depth-groups")
        return orig(*a, **k)

    rf.tree_stack_scores = crash_second
    with pytest.raises(KeyboardInterrupt):
        _train(sel, frame)
    saved = json.load(open(os.path.join(ckpt, "sweep.json")))
    keys = sorted(saved["entries"])
    assert len(keys) == 1 and keys[0].startswith("0:treestack:0:2x") \
        and keys[0].endswith(":1x2"), keys

    sel2 = make_sel()
    rf2 = sel2.models_and_grids[0][0]
    calls2 = {"n": 0}
    orig2 = rf2.tree_stack_scores

    def counting(*a, **k):
        calls2["n"] += 1
        return orig2(*a, **k)
    rf2.tree_stack_scores = counting
    model = _train(sel2, frame)
    assert calls2["n"] == 1  # only the crashed group re-dispatched
    names = {r.model_name
             for r in model.selector_summary().validation_results}
    assert len(names) == 2


def test_tree_stacked_under_mesh(monkeypatch, shared_frame, stacked_run):
    """The stacked (fold x lane) tree batch shards 2-D over an active
    mesh (rows on "data", folds on "model" when they divide it) and
    completes on the GSPMD scatter engine. Trees are discrete: sharded
    scatter+psum reduction order can flip near-tied splits, so the
    assertion is structural (mode, coverage, finite metrics) plus a
    loose value check against the shared single-device stacked run."""
    from transmogrifai_tpu.parallel.mesh import make_mesh, use_mesh
    frame = shared_frame
    s1 = stacked_run[0]
    monkeypatch.delenv("TRANSMOGRIFAI_TREE_STACKED", raising=False)
    ctx = make_mesh(n_data=4, n_model=2)
    with use_mesh(ctx):
        sweep_counters.reset()
        s2 = _train(_tree_binary_selector(), frame).selector_summary()
        c = sweep_counters.to_json()
    assert all(v["mode"] == "tree_stacked" for v in c.values()), c
    v1 = {r.model_name: r.metric_values for r in s1.validation_results}
    v2 = {r.model_name: r.metric_values for r in s2.validation_results}
    assert set(v1) == set(v2)
    for k in v1:
        for m in v1[k]:
            assert np.isfinite(v2[k][m])
            # tiny tie-prone trees: one flipped split moves auPR by ~0.05
            # on 64 validation rows; the bound catches wrong-data bugs,
            # not fp-tie reshuffles
            assert abs(v1[k][m] - v2[k][m]) <= 0.12, (k, m)


def test_batched_scatter_histogram_folds_exactly():
    """The custom_vmap rule in ops/histograms.py: a vmapped call folds
    the batch axis into the node axis and reproduces the per-slice
    histograms bit for bit, batched operands or not."""
    from transmogrifai_tpu.ops.histograms import node_bin_histogram_xla
    rng = np.random.default_rng(0)
    B, n, d, nn, nb = 3, 64, 4, 2, 8
    Xb = jnp.asarray(rng.integers(0, nb, (n, d)), jnp.int32)
    node = jnp.asarray(rng.integers(0, nn, (B, n)), jnp.int32)
    g = jnp.asarray(rng.normal(size=(B, n)), jnp.float32)
    h = jnp.asarray(rng.normal(size=(B, n)), jnp.float32)
    f = lambda nd, gg, hh: node_bin_histogram_xla(  # noqa: E731
        Xb, nd, gg, hh, n_nodes=nn, n_bins=nb)
    hg, hh_ = jax.vmap(f)(node, g, h)
    assert hg.shape == (B, nn, d, nb)
    for i in range(B):
        rg, rh = f(node[i], g[i], h[i])
        np.testing.assert_array_equal(np.asarray(hg[i]), np.asarray(rg))
        np.testing.assert_array_equal(np.asarray(hh_[i]), np.asarray(rh))
    # nested vmap (the fold x lane x class shape) under jit
    node2 = jnp.stack([node, node])
    g2 = jnp.stack([g, 2 * g])
    h2 = jnp.stack([h, 3 * h])
    out = jax.jit(lambda a, b, c: jax.vmap(jax.vmap(f))(a, b, c))(
        node2, g2, h2)
    ref = f(node[1], 2 * g[1], 3 * h[1])
    np.testing.assert_array_equal(np.asarray(out[0][1, 1]),
                                  np.asarray(ref[0]))


def test_stacked_engines_agree(monkeypatch):
    """Forced sorted engine (einsum and the interpret-mode Pallas kernel)
    under the stacked fold x lane vmaps agrees with the scatter engine."""
    from transmogrifai_tpu.selector.validator import OpCrossValidation
    rng = np.random.default_rng(1)
    n, d = 160, 3
    X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    y = jnp.asarray((rng.uniform(size=n) < 0.5).astype(np.float32))
    w = jnp.ones(n, jnp.float32)
    tr, va = OpCrossValidation(n_folds=2, seed=0).stacked_splits(n)
    jtr, jva = jnp.asarray(tr), jnp.asarray(va)
    est = OpGBTClassifier(num_rounds=2, max_depth=2, max_bins=8)
    grid = [{"learning_rate": 0.1}, {"learning_rate": 0.3}]
    plan = est.fold_sweep_plan(X, grid)
    _, codes, _ = plan[8]
    codes = codes.astype(jnp.int8)
    args = (jnp.take(codes, jtr, axis=0), jnp.take(y, jtr, axis=0),
            jnp.take(w, jtr, axis=0), jnp.take(codes, jva, axis=0))
    lnb = est.tree_stack_scalar_lnb(y)
    group = est.tree_stack_groups(grid)[0]
    s_scatter = np.asarray(
        est.tree_stack_scores(*args, group["params"], lnb))
    monkeypatch.setenv("TRANSMOGRIFAI_TREE_HIST", "sorted")
    s_einsum = np.asarray(
        est.tree_stack_scores(*args, group["params"], lnb))
    monkeypatch.setenv("TRANSMOGRIFAI_SORTED_HIST", "pallas")
    s_pallas = np.asarray(
        est.tree_stack_scores(*args, group["params"], lnb))
    assert np.abs(s_scatter - s_einsum).max() <= 1e-5
    np.testing.assert_array_equal(s_einsum, s_pallas)


def test_tree_stack_groups_and_bytes():
    est = OpGBTClassifier(num_rounds=4, max_depth=3, max_bins=16)
    groups = est.tree_stack_groups([
        {"learning_rate": 0.1}, {"learning_rate": 0.3},
        {"max_depth": 5}, {"num_trees": 8},   # alias num_trees->num_rounds
    ])
    shapes = [(g["max_depth"], g["num_rounds"], sorted(g["lanes"]))
              for g in groups]
    assert shapes == [(3, 4, [0, 1]), (5, 4, [2]), (3, 8, [3])]
    shared, per_lane = est.tree_stack_bytes(3, 1000, 500, 28, groups[0])
    assert shared > 0 and per_lane > 0
    # deeper groups keep more node stats live
    _, per_lane_deep = est.tree_stack_bytes(3, 1000, 500, 28, groups[1])
    assert per_lane_deep > per_lane
