"""Tree histogram-engine decision microbench (host-fetch fenced).

Times one full ``grow_tree`` per engine at ``HIST_ROWS`` x 28 x 64 for
depths 6 and 12, on whatever backend is live:

- ``scatter``   — flat-index scatter-add (GSPMD-safe mesh path)
- ``sorted``    — sorted-block layout + XLA einsum contraction
- ``sorted+pallas`` — same layout, fused VMEM kernel
  (ops/sorted_hist_pallas.py)

and writes ``benchmarks/HIST_ENGINES.json`` — the artifact behind the
engine defaults in ``models/trees.py`` (``_hist_mode_for`` /
``_sorted_engine_default``). Replaces the round-2..4 PALLAS_HIST.json,
whose numbers were enqueue-time artifacts (block_until_ready is not a
fence on axon; see benchmarks/_timing.py).

Run on the chip: ``python benchmarks/bench_hist_engines.py``
(CPU runs measure the interpret/einsum paths and are labeled as such).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

ROWS = int(os.environ.get("HIST_ROWS", 1_000_000))
D = 28
B = 64
DEPTHS = (6, 12)


def main() -> int:
    import jax
    import jax.numpy as jnp
    from _timing import med_fetch
    from transmogrifai_tpu.models.trees import (
        bin_data, grow_tree, quantile_bin_edges,
    )

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    X = rng.normal(size=(ROWS, D)).astype(np.float32)
    edges = quantile_bin_edges(X, B)
    Xb = jnp.asarray(bin_data(jnp.asarray(X), jnp.asarray(edges)))
    mask = jnp.ones(D, jnp.float32)
    kw = dict(n_bins=B, reg_lambda=jnp.float32(1.0),
              gamma=jnp.float32(0.0), min_child_weight=jnp.float32(1.0))

    def gh_variants(k=4):
        return [(jnp.asarray(rng.normal(size=ROWS).astype(np.float32)),
                 jnp.asarray(rng.uniform(0.2, 1.0, size=ROWS)
                             .astype(np.float32))) for _ in range(k)]

    engines = [("scatter", dict(hist="scatter")),
               ("sorted", dict(hist="sorted", sorted_engine="einsum")),
               ("sorted+pallas", dict(hist="sorted",
                                      sorted_engine="pallas"))]
    results = []
    for depth in DEPTHS:
        row = {"depth": depth}
        for name, opts in engines:
            def one(g, h, depth=depth, opts=opts):
                f, b, l, gn, pr = grow_tree(Xb, g, h, mask,
                                            max_depth=depth, **kw, **opts)
                return l
            t = med_fetch(one, gh_variants())
            row[name.replace("+", "_") + "_ms"] = round(t * 1e3, 1)
            print(f"# d{depth} {name}: {row[name.replace('+', '_') + '_ms']}"
                  " ms", file=sys.stderr)
        results.append(row)

    artifact = {
        "metric": "tree_hist_engine_microbench",
        "rows": ROWS, "features": D, "bins": B,
        "platform": platform,
        "fencing": "host-fetch (benchmarks/_timing.py)",
        "trees": results,
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "HIST_ENGINES.json")
    with open(out_path, "w") as fh:
        json.dump(artifact, fh, indent=2)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    sys.exit(main())
