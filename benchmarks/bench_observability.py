"""Observability-overhead microbench: what does span tracing cost?

Runs the SAME fixture pipeline (synthetic binary AutoML: numeric +
categorical features, logistic grid through the ModelSelector, then a
full scoring pass) three ways:

- ``base``   — span recorder disabled: every instrumented call costs one
  attribute check.
- ``spans``  — recorder enabled (the default production state): the full
  hierarchical span tree records through ingest, every DAG stage, the
  sweep, and the fused layer dispatches.
- ``export`` — spans + a ``jax.profiler`` device trace around the run +
  the merged chrome-trace JSON export (``AppMetrics.export_chrome_trace``)
  — the ``--trace-out`` / ``cli profile`` configuration.

The three configurations run INTERLEAVED for ``TRIALS`` rounds after one
shared warmup (the warmup pays all XLA compiles; fused layer programs
and model fits are jit-cache hits afterwards), and the MIN wall per
configuration is kept: span cost is deterministic host work, so the
noise-free floors are the honest comparison — medians of ~0.2s samples
on a shared box swing more than the effect being measured (single-run
medians here showed a *negative* "overhead" for the heavier config).
The acceptance bound lives in ``scripts/check_artifacts.py``: the
committed artifact's ``spans_overhead_pct`` must stay <= 5%.

Writes ``benchmarks/OBSERVABILITY.json`` (atomic), prints one JSON line.
Run: ``python benchmarks/bench_observability.py``. Knobs: OBS_ROWS,
OBS_TRIALS.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

ROWS = int(os.environ.get("OBS_ROWS", 4000))
TRIALS = int(os.environ.get("OBS_TRIALS", 7))


def _build_pipeline():
    import numpy as np

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(11)
    x1 = rng.normal(size=ROWS)
    x2 = rng.normal(size=ROWS)
    x3 = rng.exponential(size=ROWS)
    cat = rng.choice(["a", "b", "c", "d"], size=ROWS)
    logit = 1.2 * x1 - 0.7 * x2 + 0.3 * x3 + (cat == "a") * 1.0
    y = (rng.uniform(size=ROWS) < 1 / (1 + np.exp(-logit))).astype(float)
    frame = fr.HostFrame.from_dict({
        "y": (ft.RealNN, y.tolist()),
        "x1": (ft.Real, x1.tolist()),
        "x2": (ft.Real, x2.tolist()),
        "x3": (ft.Real, x3.tolist()),
        "cat": (ft.PickList, cat.tolist()),
    })

    def run_once() -> None:
        feats = FeatureBuilder.from_frame(frame, response="y")
        label = feats.pop("y")
        features = transmogrify(list(feats.values()), min_support=1)
        sel = BinaryClassificationModelSelector.with_train_validation_split(
            seed=5, models_and_parameters=[
                (OpLogisticRegression(max_iter=25),
                 [{"reg_param": r} for r in (0.0, 0.01)])])
        pred = label.transform_with(sel, features)
        model = (Workflow().set_input_frame(frame)
                 .set_result_features(pred, features).train())
        model.score(frame)

    return run_once


def _measure_interleaved(run_once, configs: dict) -> dict[str, float]:
    """``configs``: name -> (configure, teardown | None). Runs one trial
    of every configuration per round (interleaving decorrelates slow
    machine drift from the config being measured) and keeps each
    configuration's minimum wall."""
    walls: dict[str, list[float]] = {name: [] for name in configs}
    for _ in range(TRIALS):
        for name, (configure, teardown) in configs.items():
            configure()
            t0 = time.perf_counter()
            run_once()
            walls[name].append(time.perf_counter() - t0)
            if teardown is not None:
                teardown()
    return {name: min(w) for name, w in walls.items()}


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import jax

    from transmogrifai_tpu.utils.profiling import profiler
    from transmogrifai_tpu.utils.tracing import recorder

    platform = jax.devices()[0].platform
    run_once = _build_pipeline()

    # shared warmup: pay every XLA compile before any measured trial
    recorder.enable(False)
    run_once()

    trace_dir = tempfile.mkdtemp(prefix="obs_bench_trace_")
    trace_out = os.path.join(trace_dir, "trace.json")
    span_counts: list[int] = []
    trial_ix = {"n": 0}

    def spans_on():
        recorder.enable(True)
        profiler.reset(app_name="bench_observability")

    def spans_teardown():
        span_counts.append(len(recorder.spans))

    def export_on():
        # a FRESH xplane dir per trial: finalize() globs the whole
        # directory, so reusing one would re-parse (and re-attribute)
        # every earlier trial's protos in later trials
        trial_ix["n"] += 1
        recorder.enable(True)
        profiler.reset(app_name="bench_observability",
                       trace_dir=os.path.join(trace_dir,
                                              f"xplane_{trial_ix['n']}"))

    def export_teardown():
        metrics = profiler.finalize()
        metrics.export_chrome_trace(trace_out)

    import shutil
    try:
        floors = _measure_interleaved(run_once, {
            "base": (lambda: recorder.enable(False), None),
            "spans": (spans_on, spans_teardown),
            "export": (export_on, export_teardown),
        })
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)
    base_s, spans_s, export_s = (floors["base"], floors["spans"],
                                 floors["export"])
    span_count = max(span_counts)
    recorder.enable(True)

    def pct(wall: float) -> float:
        return round((wall / base_s - 1.0) * 100.0, 2)

    artifact = {
        "metric": "observability_overhead",
        "platform": platform,
        "rows": ROWS,
        "trials": TRIALS,
        "base_wall_s": round(base_s, 4),
        "spans_wall_s": round(spans_s, 4),
        "export_wall_s": round(export_s, 4),
        "spans_overhead_pct": pct(spans_s),
        "export_overhead_pct": pct(export_s),
        "span_count": span_count,
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out = os.path.join(HERE, "OBSERVABILITY.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
