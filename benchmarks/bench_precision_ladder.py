"""Precision ladder: bf16/int8 compiled serving vs the f32 master, with
the shadow gate, compile-storm counters, and the pressure rung measured
on the live server.

Topology: one small binary AutoML endpoint trained in-process, served
through ``ScoringServer`` (the same scorer/gate/pressure path the fleet
lanes run). Legs:

- **throughput**: closed-loop single-row traffic through the f32 lane,
  then through a bf16-target lane after its shadow gate promoted —
  ``speedup_bf16_x`` = bf16 rps / f32 rps. On CPU, XLA often emulates
  bf16, so the speed arm may not clear; the artifact then stands on the
  residency arm below (``check_artifacts.py`` accepts either).
- **residency**: replay each rung's REAL per-(layer, bucket) HBM
  accounting (``layer_entry_bytes``) into a fixed-budget
  ``ProgramCache`` and count whole models resident before the first
  eviction: bf16 halves every entry, so the same budget holds ~2x the
  models. Counter-asserted on cache length, not arithmetic.
- **parity**: max ``score_diff`` between the f32 master and each
  promoted rung over PARITY_ROWS held-out rows (acceptance: <= the
  gate tolerance).
- **gate_rejection**: a ``serving.precision`` fault poisons the first
  bf16 candidate — the batch must be SERVED from the f32 shadow leg
  bit-identically (zero drops), counted as a rejection, and a
  post-backoff retry must promote.
- **compile_storm**: post-warmup compiles per (bucket, rung) across the
  promoted leg — 0 means warmup covered every rung it later served.
- **pressure**: an injected dispatch OOM on an f32-active lane with
  bf16 headroom must take the precision rung BEFORE bucket shedding
  (bucket set unchanged, demotions counter == 1).

Platform honesty: the artifact records the measured backend verbatim;
``PRECISION_EXPECT_ACCEL=1`` makes a CPU fallback a hard error instead
of a mislabeled "accelerator" result.

Run: ``python benchmarks/bench_precision_ladder.py``. Knobs:
PRECISION_REQUESTS, PRECISION_TRAIN_ROWS, PRECISION_MAX_BATCH,
PRECISION_TRIALS.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

TRIALS = int(os.environ.get("PRECISION_TRIALS", 2))
REQUESTS = int(os.environ.get("PRECISION_REQUESTS", 400))
TRAIN_ROWS = int(os.environ.get("PRECISION_TRAIN_ROWS", 600))
MAX_BATCH = int(os.environ.get("PRECISION_MAX_BATCH", 32))
PARITY_ROWS = 64
TOLERANCE = 5e-2
RESIDENCY_BUDGET_MODELS_F32 = 4  # budget sized to hold ~4 f32 models


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_precision_ladder.py",
                "transmogrifai_tpu/utils/precision.py",
                "transmogrifai_tpu/serving/compiled.py",
                "transmogrifai_tpu/serving/explain.py",
                "transmogrifai_tpu/serving/server.py",
                "transmogrifai_tpu/serving/fleet.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train():
    import numpy as np
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import BinaryClassificationModelSelector
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(7)
    n = TRAIN_ROWS
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    color = rng.choice(["red", "green", "blue"], size=n)
    logit = 1.5 * x1 - x2 + (color == "red") * 1.2
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
    frame = fr.HostFrame.from_dict({
        "y": (ft.RealNN, y.tolist()),
        "x1": (ft.Real, x1.tolist()),
        "x2": (ft.Real, x2.tolist()),
        "color": (ft.PickList, color.tolist()),
    })
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify([feats["x1"], feats["x2"], feats["color"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=40), [{}])])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    rows = [{"x1": float(x1[i]), "x2": float(x2[i]),
             "color": str(color[i])} for i in range(n)]
    return model, rows


def _drive(srv, rows, n_requests: int) -> dict:
    """Closed-loop single-row traffic; best-of-TRIALS warm trials."""
    best = None
    for _ in range(TRIALS):
        lats = []
        t0 = time.perf_counter()
        for i in range(n_requests):
            r0 = time.perf_counter()
            srv.score(rows[i % len(rows)])
            lats.append((time.perf_counter() - r0) * 1e3)
        wall = time.perf_counter() - t0
        lats.sort()
        leg = {"rps": round(n_requests / wall, 1),
               "p50_ms": round(lats[len(lats) // 2], 3),
               "p99_ms": round(lats[int(len(lats) * 0.99)], 3),
               "requests": n_requests}
        if best is None or leg["rps"] > best["rps"]:
            best = leg
    return best


def _throughput(model, rows) -> tuple[dict, dict, dict]:
    from transmogrifai_tpu.serving.server import ScoringServer
    f32 = ScoringServer(model, max_batch=MAX_BATCH)
    f32.start(warmup_row=rows[0])
    try:
        leg_f32 = _drive(f32, rows, REQUESTS)
    finally:
        f32.stop()

    bf16 = ScoringServer(model, max_batch=MAX_BATCH, precision="bf16",
                         precision_tolerance=TOLERANCE)
    bf16.start(warmup_row=rows[0])
    try:
        for r in rows[:8]:  # traffic carries the gate; promotion is cheap
            bf16.score(r)
        snap = bf16.snapshot()
        assert snap["config"]["precision"]["active"] == "bf16", snap
        leg_bf16 = _drive(bf16, rows, REQUESTS)
        storm = bf16.post_warmup_compiles()
        compile_storm = {
            "max_post_warmup_per_bucket":
                max(storm.values()) if storm else 0,
            "per_bucket": {str(k): v for k, v in storm.items()},
        }
        leg_bf16["promotions"] = snap["precision"]["promotions"]
    finally:
        bf16.stop()
    return leg_f32, leg_bf16, compile_storm


def _residency(model, rows) -> dict:
    """Replay the rung's real HBM accounting into a fixed budget and
    count whole resident models (cache len, not arithmetic)."""
    from transmogrifai_tpu.serving import ProgramCache
    from transmogrifai_tpu.serving.compiled import CompiledScorer
    from transmogrifai_tpu.utils.profiling import ServingCounters

    scorer = CompiledScorer(model, max_batch=MAX_BATCH)
    scorer.warmup(rows[0])
    buckets = list(scorer.buckets)
    layers = range(len(scorer._layers))
    per_model_f32 = sum(scorer.layer_entry_bytes(li, b, "f32")
                        for li in layers for b in buckets)
    budget = RESIDENCY_BUDGET_MODELS_F32 * per_model_f32

    def models_resident(rung: str) -> int:
        cache = ProgramCache(budget_bytes=budget)
        ctr = ServingCounters()
        resident = 0
        for m in range(64):
            fp = f"model-{m}"
            lk = (lambda li: li if rung == "f32" else (rung, li))
            for li in layers:
                for b in buckets:
                    cache.get((fp, lk(li), b), lambda: object(),
                              bytes_est=scorer.layer_entry_bytes(
                                  li, b, rung),
                              counters=ctr, bucket=b)
            if cache.evictions:  # this model began evicting predecessors
                return resident
            resident = m + 1
        return resident

    n32, n16 = models_resident("f32"), models_resident("bf16")
    return {"budget_bytes": budget,
            "per_model_bytes_f32": per_model_f32,
            "models_resident_f32": n32,
            "models_resident_bf16": n16,
            "ratio": round(n16 / max(n32, 1), 3)}


def _parity(model, rows) -> dict:
    from transmogrifai_tpu.serving.compiled import CompiledScorer
    from transmogrifai_tpu.serving.fleet import score_diff
    scorer = CompiledScorer(model, max_batch=MAX_BATCH)
    sample = rows[:PARITY_ROWS]
    ref = list(scorer.score_batch(sample, precision="f32"))
    out = {}
    for rung in ("bf16", "int8"):
        docs = list(scorer.score_batch(sample, precision=rung))
        out[f"{rung}_max_score_diff"] = float(
            max(score_diff(a, b) for a, b in zip(ref, docs)))
    out.update({"tolerance": TOLERANCE, "rows": len(sample)})
    return out


def _gate_rejection(model, rows) -> dict:
    from transmogrifai_tpu.serving.server import ScoringServer
    from transmogrifai_tpu.utils.faults import fault_plan
    srv = ScoringServer(model, max_batch=MAX_BATCH, precision="bf16",
                        precision_backoff=2)
    srv.start(warmup_row=rows[0])
    try:
        with fault_plan("transient@serving.precision#0"):
            doc = srv.score(rows[0])
        snap = srv.snapshot()
        ref = list(srv.scorer.score_batch([rows[0]],
                                          precision="f32"))[0]
        served_f32 = (doc == ref
                      and snap["config"]["precision"]["active"] == "f32")
        for r in rows[1:8]:
            srv.score(r)
        snap2 = srv.snapshot()
        return {"rejections": snap["precision"]["rejections"],
                "served_f32": bool(served_f32),
                "drops": 0 if doc is not None else 1,
                "later_promoted":
                    snap2["config"]["precision"]["active"] == "bf16"
                    and snap2["precision"]["promotions"] >= 1}
    finally:
        srv.stop()


def _pressure(model, rows) -> dict:
    from transmogrifai_tpu.serving.server import ScoringServer
    from transmogrifai_tpu.utils.faults import fault_plan
    srv = ScoringServer(model, max_batch=MAX_BATCH, precision="bf16",
                        retries=0)
    srv.start(warmup_row=rows[0])
    try:
        buckets_before = list(srv.scorer.buckets)
        with fault_plan("oom@serving.dispatch#0"):
            doc = srv.score(rows[0])
        snap = srv.snapshot()
        shed = len(buckets_before) - len(list(srv.scorer.buckets))
        return {"demotions": snap["precision"]["demotions"],
                "precision_rung_first":
                    snap["precision"]["demotions"] == 1 and shed == 0
                    and doc is not None,
                "buckets_shed_before_demotion": shed}
    finally:
        srv.stop()


def main() -> int:
    os.environ.setdefault("TRANSMOGRIFAI_SILENT", "1")
    import jax
    platform = jax.devices()[0].platform
    if os.environ.get("PRECISION_EXPECT_ACCEL") == "1" \
            and platform == "cpu":
        print("PRECISION_EXPECT_ACCEL=1 but backend is cpu", flush=True)
        return 1

    model, rows = _train()
    leg_f32, leg_bf16, compile_storm = _throughput(model, rows)
    residency = _residency(model, rows)
    parity = _parity(model, rows)
    rejection = _gate_rejection(model, rows)
    pressure = _pressure(model, rows)

    doc = {
        "metric": "precision_ladder",
        "unit": "rps",
        "platform": platform,
        "requests": 2 * TRIALS * REQUESTS,
        "train_rows": TRAIN_ROWS,
        "max_batch": MAX_BATCH,
        "f32_rps": leg_f32["rps"],
        "bf16_rps": leg_bf16["rps"],
        "f32": leg_f32,
        "bf16": leg_bf16,
        "speedup_bf16_x": round(leg_bf16["rps"] / leg_f32["rps"], 3),
        "residency": residency,
        "parity": parity,
        "gate_rejection": rejection,
        "compile_storm": compile_storm,
        "pressure": pressure,
        "code_fingerprint": _code_fingerprint(),
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    out = os.path.join(HERE, "PRECISION_LADDER.json")
    with open(out, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    print(json.dumps(doc, indent=1))

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from check_artifacts import validate_artifact
    errors = validate_artifact(doc)
    for e in errors:
        print(f"SCHEMA: {e}", flush=True)
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
