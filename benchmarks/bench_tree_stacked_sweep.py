"""Fold x grid-stacked TREE sweep microbench (host-fetch fenced).

Times one tree-family (fold x grid) CV sweep unit — train every grid
lane on every fold from the dataset-level bin codes, score the
validation folds, pull the metric batch — at ``SWEEP_ROWS`` x
``SWEEP_COLS`` x ``SWEEP_BINS``, three ways:

- ``per_point``    — per-fold loop with sequential per-grid-point fits
  and per-model scoring + metric pulls: the base ``Predictor`` contract
  (no batching at all; k x L dispatches and k x L host syncs).
- ``per_fold``     — per-fold loop with the family's bin-once
  ``grid_fit_arrays`` and the same-shape batched scorer + one metric
  sync per fold: the pre-round-8 tree sweep (k dispatches, k syncs).
- ``tree_stacked`` — this PR: the whole k folds x L lanes depth-group as
  ONE compiled program (``tree_stack_scores``) + the fold-batched
  metric: one dispatch and ONE host sync for the group.

Writes ``benchmarks/TREE_STACKED_SWEEP.json`` and prints one JSON line.
The stacked path's headline win is dispatch/host-sync latency (k x L
fewer round trips — decisive on a tunneled TPU); the recorded
``host_syncs``/``dispatches`` blocks are the structural counts at the
selector's accounting granularity (``SweepCounters``), which is what
the gating default is argued from. The CPU default only flips ON if
``speedup_vs_per_fold`` measures >= 1.0 here. Run:
``python benchmarks/bench_tree_stacked_sweep.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

ROWS = int(os.environ.get("SWEEP_ROWS", 100_000))
FOLDS = int(os.environ.get("SWEEP_FOLDS", 3))
D = int(os.environ.get("SWEEP_COLS", 28))
BINS = int(os.environ.get("SWEEP_BINS", 64))
ROUNDS = int(os.environ.get("SWEEP_ROUNDS", 10))
DEPTH = int(os.environ.get("SWEEP_DEPTH", 6))
#: one depth-group of same-shape lanes (the default AutoML tree grids
#: vary learning_rate/reg_lambda inside a depth far more often than
#: depth itself once grouped)
N_GRID = int(os.environ.get("SWEEP_GRID", 4))
REPEATS = int(os.environ.get("SWEEP_REPEATS", 1))


def main() -> int:
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.models.trees import OpGBTClassifier
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    platform = jax.devices()[0].platform
    grid = [{"learning_rate": lr, "reg_lambda": rl}
            for lr in (0.1, 0.3) for rl in (0.5, 1.0)][:N_GRID]
    est = OpGBTClassifier(num_rounds=ROUNDS, max_depth=DEPTH,
                          max_bins=BINS)
    ev = OpBinaryClassificationEvaluator()

    rng = np.random.default_rng(0)
    Xh = rng.normal(size=(ROWS, D)).astype(np.float32)
    logits = 1.2 * Xh[:, 0] - 0.7 * Xh[:, 1] + 0.5 * Xh[:, 2] * Xh[:, 3]
    yh = (rng.uniform(size=ROWS) < 1.0 / (1.0 + np.exp(-logits))
          ).astype(np.float32)
    X = jnp.asarray(Xh)
    y = jnp.asarray(yh)
    w = jnp.ones(ROWS, jnp.float32)
    tr_idx, va_idx = OpCrossValidation(n_folds=FOLDS).stacked_splits(ROWS)
    jtr, jva = jnp.asarray(tr_idx), jnp.asarray(va_idx)

    plan = est.fold_sweep_plan(X, grid)
    _, codes, _ = plan[BINS]
    if BINS <= 127:
        codes = codes.astype(jnp.int8)
    lnb = est.tree_stack_scalar_lnb(y)
    group = est.tree_stack_groups(grid)[0]

    def per_point():
        """Per-fold loop, base-contract sequential per-point fits with
        per-model scoring + metric pulls (k x L syncs)."""
        vals = []
        for f in range(FOLDS):
            Xtr, ytr, wtr = X[jtr[f]], y[jtr[f]], w[jtr[f]]
            fold = []
            for g in grid:
                m = est.fit_arrays(Xtr, ytr, wtr, {**est.params, **g})
                pred = m.predict_arrays(X[jva[f]])
                fold.append(ev.metric_from_arrays(y[jva[f]], pred, "auPR"))
            vals.append(fold)
        return np.asarray(vals)

    def per_fold():
        """Per-fold loop, bin-once grid trainer + same-shape batched
        scorer + one metric sync per fold (the r06 tree sweep)."""
        vals = []
        for f in range(FOLDS):
            Xtr, ytr, wtr = X[jtr[f]], y[jtr[f]], w[jtr[f]]
            models = est.grid_fit_arrays(Xtr, ytr, wtr, grid,
                                         _fold_plan=plan,
                                         _fold_rows=jtr[f])
            scores = est.grid_predict_scores(models, X[jva[f]])
            vals.append(ev.metric_batch_scores(y[jva[f]], scores, "auPR"))
        return np.stack(vals)

    def tree_stacked():
        """This PR: one fused stacked train+score for the whole depth-
        group + one fold-batched metric pull (the selector fast path's
        exact unit)."""
        scores = est.tree_stack_scores(
            jnp.take(codes, jtr, axis=0), jnp.take(y, jtr, axis=0),
            jnp.take(w, jtr, axis=0), jnp.take(codes, jva, axis=0),
            group["params"], lnb)
        return np.asarray(ev.metric_batch_scores_folds(
            jnp.take(y, jva, axis=0), scores, "auPR"))

    def timed(fn):
        out0 = fn()  # warmup/compile burn; metric pulls fence the device
        ts = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), out0

    t_stacked, m_stacked = timed(tree_stacked)
    t_fold, m_fold = timed(per_fold)
    t_point, m_point = timed(per_point)
    parity = float(np.max(np.abs(m_stacked - np.asarray(m_fold))))
    parity_exact = float(np.max(np.abs(m_stacked - m_point)))

    result = {
        "metric": "tree_stacked_sweep",
        "unit": "s",
        "platform": platform,
        "rows": ROWS, "cols": D, "bins": BINS, "folds": FOLDS,
        "grid_points": len(grid), "rounds": ROUNDS, "depth": DEPTH,
        "groups": 1,
        "tree_stacked_s": round(t_stacked, 3),
        "per_fold_s": round(t_fold, 3),
        "per_point_s": round(t_point, 3),
        "speedup_vs_per_fold": round(t_fold / t_stacked, 2),
        "speedup_vs_per_point": round(t_point / t_stacked, 2),
        "metric_parity_stacked_vs_per_fold": parity,
        "metric_delta_stacked_vs_exact_per_point": parity_exact,
        # structural counts at the SweepCounters accounting granularity
        "dispatches": {"tree_stacked": 1, "per_fold": FOLDS,
                       "per_point": FOLDS * len(grid)},
        "host_syncs": {"tree_stacked": 1, "per_fold": FOLDS,
                       "per_point": FOLDS * len(grid)},
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "TREE_STACKED_SWEEP.json")
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
