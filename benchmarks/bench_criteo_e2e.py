"""Criteo-shaped END-TO-END benchmark: ingest -> transmogrify (hashing) ->
sanity check -> LR sweep, with the host-encode / device-compute overlap
measured explicitly.

BASELINE.json / SURVEY §7 hard part (b): the Criteo-1TB config (13 numeric
+ 26 categorical click-log columns, high-cardinality hashing) stresses the
HOST side (string -> codes -> hashed blocks) as much as the device. This
bench builds the same shape synthetically and times:

1. ``encode``      — native dictionary encoding of all 26 categorical
                     columns at ``CRITEO_E2E_ROWS`` (default 10M).
2. ``overlap``     — chunked hashed-block build where the host encodes
                     chunk k+1 WHILE the device reduces chunk k's moment
                     monoid (async dispatch): wall for serial vs
                     overlapped passes. On a real TPU the overlapped wall
                     approaches max(host, device); on the CPU backend both
                     contend for the same cores and the ratio is ~1.
3. ``automl``      — the full framework path at ``CRITEO_TRAIN_ROWS``
                     (default 1M): transmogrify (SmartText hashing for the
                     high-cardinality columns, pivot for the low ones) ->
                     SanityChecker -> 3-fold LR grid sweep -> holdout.
4. ``fe_fusion``   — round 14: the same FE pipeline measured HOST-side
                     (token hashing vectorizer, stage-by-stage) vs
                     DEVICE-resident (murmur hashing + bucketless FE
                     fused into one jitted program), plus double-buffered
                     chunked ingest (decode N+1 overlaps device FE of N),
                     fused-vs-unfused prediction parity, and the
                     TRANSMOGRIFAI_FE_FUSED=0 byte-for-byte restore
                     proof. Emits ``benchmarks/INGEST_FE_FUSION.json``
                     (schema ``ingest_fe_fusion``) at ``CRITEO_FE_ROWS``
                     (default min(rows, 200k)).

Prints ONE JSON line. Quick pass:
``CRITEO_E2E_ROWS=200000 CRITEO_TRAIN_ROWS=100000 JAX_PLATFORMS=cpu
python benchmarks/bench_criteo_e2e.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_ROWS = int(os.environ.get("CRITEO_E2E_ROWS", 10_000_000))
TRAIN_ROWS = int(os.environ.get("CRITEO_TRAIN_ROWS", 1_000_000))
HASH_FEATURES = int(os.environ.get("CRITEO_HASH_FEATURES", 32))
CHUNK = int(os.environ.get("CRITEO_CHUNK", 250_000))
FE_ROWS = int(os.environ.get("CRITEO_FE_ROWS",
                             min(N_ROWS, 200_000)))
FE_CHUNKS = int(os.environ.get("CRITEO_FE_CHUNKS", 8))
N_NUM, N_CAT = 13, 26
CARDS = [10, 100, 1000, 10_000, 100_000]

FUSION_ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "INGEST_FE_FUSION.json")


def synth(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    nums = {f"i{j}": rng.normal(size=n) for j in range(N_NUM)}
    cats = {}
    cat_codes = {}
    for j in range(N_CAT):
        card = CARDS[j % len(CARDS)]
        codes = rng.integers(0, card, n)
        vals = np.array([f"c{j}_{v}" for v in range(card)], dtype=object)
        col = vals[codes]
        col[rng.uniform(size=n) < 0.05] = None
        cats[f"c{j}"] = col
        cat_codes[f"c{j}"] = codes
    # label with numeric + low-card categorical signal (auROC is
    # meaningful, not coin-flip)
    effect = (np.linspace(-1.0, 1.0, 10))[cat_codes["c0"] % 10]
    logits = (0.8 * nums["i0"] - 0.5 * nums["i1"]
              + 0.4 * np.tanh(nums["i2"]) + effect)
    label = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(float)
    return nums, cats, label


def _fe_fusion_section(nums, cats, label, automl_model, automl_frame,
                       platform: str) -> dict:
    """Section 4 (round 14): host-side vs device-fused FE over the
    Criteo-shaped columns, double-buffered chunked ingest overlap,
    fused-vs-unfused prediction parity, and the FE_FUSED=0 byte-for-byte
    restore proof. Returns the ``ingest_fe_fusion`` artifact document."""
    import jax
    import numpy as np

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.dag import DagExecutor
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.ingest_fusion import ChunkPrefetcher
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.readers.base import CustomReader
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.profiling import ingest_counters
    from transmogrifai_tpu.utils.tracing import recorder
    from transmogrifai_tpu.workflow import Workflow

    m = FE_ROWS
    phases: dict = {}
    art: dict = {"metric": "ingest_fe_fusion", "unit": "s",
                 "platform": platform, "rows": m, "phases": phases,
                 "hash_features": HASH_FEATURES}
    # the fused legs REQUIRE the gate on — force it for this section and
    # restore whatever the caller exported (a FE_FUSED=0 run of the full
    # bench must not crash here; the section itself measures both states)
    env_prev = os.environ.get("TRANSMOGRIFAI_FE_FUSED")
    os.environ["TRANSMOGRIFAI_FE_FUSED"] = "1"

    t0 = time.time()
    cols = {f"i{j}": (ft.Real, nums[f"i{j}"][:m]) for j in range(N_NUM)}
    for name, col in cats.items():
        cols[name] = (ft.Text, col[:m])
    cols["label"] = (ft.RealNN, label[:m])
    frame = fr.HostFrame.from_dict(cols)
    phases["build_s"] = round(time.time() - t0, 2)

    def build_model(text_vectorizer: str):
        feats = FeatureBuilder.from_frame(frame, response="label")
        lab = feats.pop("label")
        vec = transmogrify(list(feats.values()),
                           num_hash_features=HASH_FEATURES,
                           text_vectorizer=text_vectorizer)
        t1 = time.time()
        model = (Workflow().set_input_frame(frame)
                 .set_result_features(vec).train())
        return model, vec.name, time.time() - t1

    def fe_leg(model, vec_name: str):
        """One warm pass (compiles + uploads), then the timed pass; the
        host-FE wall is the stage.transform span total in the window."""
        def pull(d):
            col = d.device.get(vec_name)
            if col is not None:
                jax.block_until_ready(col.values)
            else:
                d.host_col(vec_name)
            return d
        pull(model.transform(frame))  # warm
        t1 = time.time()
        d = pull(model.transform(frame))
        wall = time.time() - t1
        host_fe = sum(s.wall_s for s in recorder.spans
                      if s.name == "stage.transform" and s.t0 >= t1)
        del d
        return wall, host_fe

    # --- host-FE leg: the pre-round-14 shape (per-row token hashing on
    # host, stage-by-stage materialization) -------------------------------
    model_host, vec_host, fit_host_s = build_model("hash")
    phases["fit_host_s"] = round(fit_host_s, 2)
    host_wall, host_fe_s = fe_leg(model_host, vec_host)
    phases["fe_host_leg_s"] = round(host_wall, 3)
    del model_host

    # --- fused leg: device murmur hashing, whole FE DAG as fused device
    # programs over the HBM-resident frame --------------------------------
    model_dev, vec_dev, fit_dev_s = build_model("hash_device")
    phases["fit_device_s"] = round(fit_dev_s, 2)
    ingest_counters.reset()
    fused_wall, fused_fe_s = fe_leg(model_dev, vec_dev)
    phases["fe_fused_leg_s"] = round(fused_wall, 3)
    fused_counters = ingest_counters.to_json()

    unfused_share = host_fe_s / max(host_wall, 1e-9)
    fused_share = fused_fe_s / max(fused_wall, 1e-9)
    # a fully-removed host FE phase gives share 0: report the ratio
    # capped at 1000x rather than dividing by zero
    cut = (unfused_share / fused_share if fused_share > 0
           else min(unfused_share * 1e6, 1000.0))
    art["host_fe_wall_share"] = {
        "unfused_share": round(unfused_share, 4),
        "fused_share": round(fused_share, 6),
        "cut_ratio": round(min(cut, 1000.0), 2),
        "host_fe_wall_s": round(host_fe_s, 3),
        "note": ("share of the FE transform wall spent executing host-side"
                 " stage code; the fused leg runs every stage inside the "
                 "jitted device program"),
    }

    # --- double-buffered chunked ingest: decode chunk N+1 on the prefetch
    # thread while chunk N's fused FE program runs -------------------------
    # numeric-only pipeline: chunk-stable jit keys (text vocab is
    # batch-local aux and would retrace per chunk; fixing streaming text
    # vocab is the serving frozen-vocab pattern, out of scope here)
    num_feats = FeatureBuilder.from_frame(
        frame.select([f"i{j}" for j in range(N_NUM)] + ["label"]),
        response="label")
    num_lab = num_feats.pop("label")
    num_vec = transmogrify(list(num_feats.values()), label=num_lab)
    stream_model = (Workflow().set_input_frame(frame)
                    .set_result_features(num_vec).train())
    sv_name = num_vec.name

    chunk = max(m // FE_CHUNKS, 1)
    bounds = [(lo, min(lo + chunk, m)) for lo in range(0, m, chunk)]
    bounds = [b for b in bounds if b[1] - b[0] == chunk]  # equal jit keys

    def make_records(lo: int, hi: int) -> list:
        names = [f"i{j}" for j in range(N_NUM)]
        arrs = [nums[n][lo:hi] for n in names]
        return [{n: float(a[i]) for n, a in zip(names, arrs)}
                for i in range(hi - lo)]

    def decode(b):
        return stream_model._ingest_frame(
            CustomReader(records=make_records(*b)))

    def run_chunk(f):
        d = stream_model.transform(f)
        jax.block_until_ready(d.device[sv_name].values)
        return d

    run_chunk(decode(bounds[0]))  # warm: compile outside the window
    ingest_counters.reset()
    pf = ChunkPrefetcher(bounds, decode, depth=2)
    t1 = time.time()
    for f in pf:
        run_chunk(f)
    wall = time.time() - t1
    phases["overlap_wall_s"] = round(wall, 3)
    decode_s, wait_s = pf.decode_s, pf.wait_s
    ratio = (max(0.0, min(1.0, (decode_s - wait_s) / decode_s))
             if decode_s > 0 else 0.0)
    art["overlap"] = {
        "chunks": len(bounds), "chunk_rows": chunk,
        "decode_s": round(decode_s, 3),
        "consumer_wait_s": round(wait_s, 3),
        "wall_s": round(wall, 3),
        "serial_estimate_s": round(decode_s + (wall - wait_s), 3),
        "ratio": round(ratio, 3),
        "note": ("ratio = fraction of background decode seconds the "
                 "consumer never waited for (1 = decode fully hidden "
                 "behind device compute); on the CPU backend decode and "
                 "'device' FE share cores, so the honest ratio is "
                 "core-contention-bounded — the TPU runlist measures the "
                 "real overlap"),
    }

    # --- fused-vs-unfused prediction parity + FE_FUSED=0 restore proof ----
    t1 = time.time()
    k = min(m, automl_frame.n_rows, 50_000)
    sub = automl_frame.take(np.arange(k))
    pred_name = automl_model._prediction_feature().name

    def pos_scores():
        d = automl_model.transform(sub)
        return np.asarray(d.device[pred_name].pos_score())

    s_fused = pos_scores()
    os.environ["TRANSMOGRIFAI_FE_FUSED"] = "0"
    try:
        ingest_counters.reset()
        s_unfused = pos_scores()
        off_counters = ingest_counters.to_json()
        # the explicit pre-fusion execution: per-layer apply on a fresh
        # executor — FE_FUSED=0 must match it byte-for-byte
        v0 = np.asarray(
            model_dev.transform(frame).host_col(vec_dev).values)
        data = model_dev._ingest(frame)
        ex = DagExecutor()
        for layer in model_dev.dag:
            data = ex.apply_layer(data, layer)
        v_ref = np.asarray(data.host_col(vec_dev).values)
        bitwise = bool(np.array_equal(v0, v_ref))
    finally:
        if env_prev is None:
            os.environ.pop("TRANSMOGRIFAI_FE_FUSED", None)
        else:
            os.environ["TRANSMOGRIFAI_FE_FUSED"] = env_prev
    phases["parity_s"] = round(time.time() - t1, 2)
    art["parity"] = {
        "prediction_max_abs": float(np.max(np.abs(s_fused - s_unfused))),
        "rows": int(k),
    }
    art["fused_disabled"] = {
        "fused_programs": int(off_counters["feFusedPrograms"]),
        "bitwise_equal": bitwise,
    }
    art["counters"] = {"fused_leg": fused_counters,
                       "disabled_leg": off_counters}
    art["value"] = phases["fe_fused_leg_s"]
    return art


def main() -> int:
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax
        try:
            jax.config.update("jax_platforms", want)
        except RuntimeError:
            pass
    import jax
    import jax.numpy as jnp

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.ops.vectorizers.hashing import hash_token
    from transmogrifai_tpu.preparators import SanityChecker
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, DataSplitter,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.dict_encode import dict_encode
    from transmogrifai_tpu.workflow import Workflow

    platform = jax.devices()[0].platform
    result: dict = {"metric": "criteo_e2e", "unit": "s",
                    "platform": platform, "rows": N_ROWS,
                    "train_rows": TRAIN_ROWS}

    t0 = time.time()
    nums, cats, label = synth(N_ROWS)
    result["synth_s"] = round(time.time() - t0, 2)

    # --- 1. full-size native dictionary encode (26 columns) --------------
    t0 = time.time()
    encoded = {name: dict_encode(col) for name, col in cats.items()}
    result["encode_s"] = round(time.time() - t0, 2)
    result["encode_cells_per_s"] = round(N_ROWS * N_CAT
                                         / max(time.time() - t0, 1e-9))

    # --- 2. host-encode / device-compute overlap (chunked) ----------------
    # per-unique hashed table per column (vocab is small vs rows), then
    # per chunk: gather rows (host) -> device moments (async)
    H = HASH_FEATURES
    tables = {}
    for name, (codes, vocab) in encoded.items():
        tab = np.zeros((len(vocab) + 1, H), np.float32)  # last row = null
        for u, v in enumerate(vocab):
            tab[u, hash_token(v, H)] += 1.0
        tables[name] = tab

    @jax.jit
    def moments(x):
        return jnp.sum(x, axis=0), jnp.sum(x * x, axis=0)

    def host_chunk(lo, hi):
        blocks = [tables[name][np.where(codes[lo:hi] >= 0,
                                        codes[lo:hi], len(vocab))]
                  for name, (codes, vocab) in encoded.items()]
        blocks.append(np.stack([nums[f"i{j}"][lo:hi]
                                for j in range(N_NUM)], axis=1)
                      .astype(np.float32))
        return np.concatenate(blocks, axis=1)

    # bounds cover only real rows (a CHUNK larger than N_ROWS/2 would
    # otherwise produce empty slices and a meaningless ratio), capped at 8
    # chunks so the overlap section stays bounded at any N_ROWS
    chunk = min(CHUNK, max(N_ROWS // 2, 1))
    bounds = [(lo, min(lo + chunk, N_ROWS))
              for lo in range(0, N_ROWS, chunk)][:8]
    n_chunks = len(bounds)

    # warm the compiled moments program for every chunk shape OUTSIDE the
    # timed region — otherwise the serial pass absorbs the one-time XLA
    # compile and the 'overlap speedup' is inflated by compile savings
    for lo, hi in {(0, bounds[0][1]), bounds[-1]}:
        jax.block_until_ready(moments(jnp.asarray(host_chunk(lo, hi))))

    t0 = time.time()
    acc = None
    for lo, hi in bounds:             # serial: block on each device result
        x = host_chunk(lo, hi)
        s, s2 = jax.block_until_ready(moments(jnp.asarray(x)))
        acc = (s, s2) if acc is None else (acc[0] + s, acc[1] + s2)
    serial_s = time.time() - t0

    t0 = time.time()
    pending = []
    for lo, hi in bounds:             # overlapped: dispatch, keep encoding
        x = host_chunk(lo, hi)
        pending.append(moments(jnp.asarray(x)))  # async under dispatch
    jax.block_until_ready(pending)
    overlap_s = time.time() - t0
    result["overlap"] = {
        "chunks": n_chunks, "chunk_rows": chunk,
        "hashed_width": int(sum(t.shape[1] for t in tables.values())
                            + N_NUM),
        "serial_s": round(serial_s, 2),
        "overlapped_s": round(overlap_s, 2),
        "speedup": round(serial_s / max(overlap_s, 1e-9), 3),
        "note": ("host encodes chunk k+1 while the device reduces chunk "
                 "k; on the CPU backend host and 'device' share cores so "
                 "speedup ~1 — the TPU runlist measures the real overlap"),
    }

    # --- 3. full framework path at TRAIN_ROWS -----------------------------
    m = TRAIN_ROWS
    cols = {f"i{j}": (ft.Real, nums[f"i{j}"][:m]) for j in range(N_NUM)}
    for name, col in cats.items():
        cols[name] = (ft.Text, col[:m])
    cols["label"] = (ft.RealNN, label[:m])
    frame = fr.HostFrame.from_dict(cols)

    t0 = time.time()
    feats = FeatureBuilder.from_frame(frame, response="label")
    lab = feats.pop("label")
    vec = transmogrify(list(feats.values()), num_hash_features=H)
    checked = lab.transform_with(SanityChecker(), vec)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, seed=42,
        models_and_parameters=[
            (OpLogisticRegression(max_iter=50),
             [{"reg_param": r} for r in (0.001, 0.01, 0.1, 0.3)])],
        splitter=DataSplitter(reserve_test_fraction=0.1, seed=42))
    pred = lab.transform_with(sel, checked)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred).train())
    automl_s = time.time() - t0
    s = model.selector_summary()
    holdout = s.holdout_evaluation.get("binary classification", {})
    result["automl"] = {
        "wall_s": round(automl_s, 2),
        "holdout_auroc": round(float(holdout.get("au_roc", float("nan"))),
                               4),
        "best": s.best_model_name,
        "vector_width": None,
    }
    try:
        data = model.transform(frame)
        result["automl"]["vector_width"] = int(
            data.vector_meta(pred.origin_stage.input_names[1]).size)
    except Exception:
        pass

    # --- 4. fused ingest/FE (round 14) ------------------------------------
    t0 = time.time()
    art = _fe_fusion_section(nums, cats, label, model, frame, platform)
    art["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    from transmogrifai_tpu.utils.durable import atomic_json_dump
    atomic_json_dump(art, FUSION_ARTIFACT)
    result["fe_fusion"] = art
    result["fe_fusion_s"] = round(time.time() - t0, 2)

    result["value"] = result["automl"]["wall_s"]
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
