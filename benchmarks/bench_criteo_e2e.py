"""Criteo-shaped END-TO-END benchmark: ingest -> transmogrify (hashing) ->
sanity check -> LR sweep, with the host-encode / device-compute overlap
measured explicitly.

BASELINE.json / SURVEY §7 hard part (b): the Criteo-1TB config (13 numeric
+ 26 categorical click-log columns, high-cardinality hashing) stresses the
HOST side (string -> codes -> hashed blocks) as much as the device. This
bench builds the same shape synthetically and times:

1. ``encode``      — native dictionary encoding of all 26 categorical
                     columns at ``CRITEO_E2E_ROWS`` (default 10M).
2. ``overlap``     — chunked hashed-block build where the host encodes
                     chunk k+1 WHILE the device reduces chunk k's moment
                     monoid (async dispatch): wall for serial vs
                     overlapped passes. On a real TPU the overlapped wall
                     approaches max(host, device); on the CPU backend both
                     contend for the same cores and the ratio is ~1.
3. ``automl``      — the full framework path at ``CRITEO_TRAIN_ROWS``
                     (default 1M): transmogrify (SmartText hashing for the
                     high-cardinality columns, pivot for the low ones) ->
                     SanityChecker -> 3-fold LR grid sweep -> holdout.

Prints ONE JSON line. Quick pass:
``CRITEO_E2E_ROWS=200000 CRITEO_TRAIN_ROWS=100000 JAX_PLATFORMS=cpu
python benchmarks/bench_criteo_e2e.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_ROWS = int(os.environ.get("CRITEO_E2E_ROWS", 10_000_000))
TRAIN_ROWS = int(os.environ.get("CRITEO_TRAIN_ROWS", 1_000_000))
HASH_FEATURES = int(os.environ.get("CRITEO_HASH_FEATURES", 32))
CHUNK = int(os.environ.get("CRITEO_CHUNK", 250_000))
N_NUM, N_CAT = 13, 26
CARDS = [10, 100, 1000, 10_000, 100_000]


def synth(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    nums = {f"i{j}": rng.normal(size=n) for j in range(N_NUM)}
    cats = {}
    cat_codes = {}
    for j in range(N_CAT):
        card = CARDS[j % len(CARDS)]
        codes = rng.integers(0, card, n)
        vals = np.array([f"c{j}_{v}" for v in range(card)], dtype=object)
        col = vals[codes]
        col[rng.uniform(size=n) < 0.05] = None
        cats[f"c{j}"] = col
        cat_codes[f"c{j}"] = codes
    # label with numeric + low-card categorical signal (auROC is
    # meaningful, not coin-flip)
    effect = (np.linspace(-1.0, 1.0, 10))[cat_codes["c0"] % 10]
    logits = (0.8 * nums["i0"] - 0.5 * nums["i1"]
              + 0.4 * np.tanh(nums["i2"]) + effect)
    label = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(float)
    return nums, cats, label


def main() -> int:
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax
        try:
            jax.config.update("jax_platforms", want)
        except RuntimeError:
            pass
    import jax
    import jax.numpy as jnp

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.ops.vectorizers.hashing import hash_token
    from transmogrifai_tpu.preparators import SanityChecker
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, DataSplitter,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.dict_encode import dict_encode
    from transmogrifai_tpu.workflow import Workflow

    platform = jax.devices()[0].platform
    result: dict = {"metric": "criteo_e2e", "unit": "s",
                    "platform": platform, "rows": N_ROWS,
                    "train_rows": TRAIN_ROWS}

    t0 = time.time()
    nums, cats, label = synth(N_ROWS)
    result["synth_s"] = round(time.time() - t0, 2)

    # --- 1. full-size native dictionary encode (26 columns) --------------
    t0 = time.time()
    encoded = {name: dict_encode(col) for name, col in cats.items()}
    result["encode_s"] = round(time.time() - t0, 2)
    result["encode_cells_per_s"] = round(N_ROWS * N_CAT
                                         / max(time.time() - t0, 1e-9))

    # --- 2. host-encode / device-compute overlap (chunked) ----------------
    # per-unique hashed table per column (vocab is small vs rows), then
    # per chunk: gather rows (host) -> device moments (async)
    H = HASH_FEATURES
    tables = {}
    for name, (codes, vocab) in encoded.items():
        tab = np.zeros((len(vocab) + 1, H), np.float32)  # last row = null
        for u, v in enumerate(vocab):
            tab[u, hash_token(v, H)] += 1.0
        tables[name] = tab

    @jax.jit
    def moments(x):
        return jnp.sum(x, axis=0), jnp.sum(x * x, axis=0)

    def host_chunk(lo, hi):
        blocks = [tables[name][np.where(codes[lo:hi] >= 0,
                                        codes[lo:hi], len(vocab))]
                  for name, (codes, vocab) in encoded.items()]
        blocks.append(np.stack([nums[f"i{j}"][lo:hi]
                                for j in range(N_NUM)], axis=1)
                      .astype(np.float32))
        return np.concatenate(blocks, axis=1)

    # bounds cover only real rows (a CHUNK larger than N_ROWS/2 would
    # otherwise produce empty slices and a meaningless ratio), capped at 8
    # chunks so the overlap section stays bounded at any N_ROWS
    chunk = min(CHUNK, max(N_ROWS // 2, 1))
    bounds = [(lo, min(lo + chunk, N_ROWS))
              for lo in range(0, N_ROWS, chunk)][:8]
    n_chunks = len(bounds)

    # warm the compiled moments program for every chunk shape OUTSIDE the
    # timed region — otherwise the serial pass absorbs the one-time XLA
    # compile and the 'overlap speedup' is inflated by compile savings
    for lo, hi in {(0, bounds[0][1]), bounds[-1]}:
        jax.block_until_ready(moments(jnp.asarray(host_chunk(lo, hi))))

    t0 = time.time()
    acc = None
    for lo, hi in bounds:             # serial: block on each device result
        x = host_chunk(lo, hi)
        s, s2 = jax.block_until_ready(moments(jnp.asarray(x)))
        acc = (s, s2) if acc is None else (acc[0] + s, acc[1] + s2)
    serial_s = time.time() - t0

    t0 = time.time()
    pending = []
    for lo, hi in bounds:             # overlapped: dispatch, keep encoding
        x = host_chunk(lo, hi)
        pending.append(moments(jnp.asarray(x)))  # async under dispatch
    jax.block_until_ready(pending)
    overlap_s = time.time() - t0
    result["overlap"] = {
        "chunks": n_chunks, "chunk_rows": chunk,
        "hashed_width": int(sum(t.shape[1] for t in tables.values())
                            + N_NUM),
        "serial_s": round(serial_s, 2),
        "overlapped_s": round(overlap_s, 2),
        "speedup": round(serial_s / max(overlap_s, 1e-9), 3),
        "note": ("host encodes chunk k+1 while the device reduces chunk "
                 "k; on the CPU backend host and 'device' share cores so "
                 "speedup ~1 — the TPU runlist measures the real overlap"),
    }

    # --- 3. full framework path at TRAIN_ROWS -----------------------------
    m = TRAIN_ROWS
    cols = {f"i{j}": (ft.Real, nums[f"i{j}"][:m]) for j in range(N_NUM)}
    for name, col in cats.items():
        cols[name] = (ft.Text, col[:m])
    cols["label"] = (ft.RealNN, label[:m])
    frame = fr.HostFrame.from_dict(cols)

    t0 = time.time()
    feats = FeatureBuilder.from_frame(frame, response="label")
    lab = feats.pop("label")
    vec = transmogrify(list(feats.values()), num_hash_features=H)
    checked = lab.transform_with(SanityChecker(), vec)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, seed=42,
        models_and_parameters=[
            (OpLogisticRegression(max_iter=50),
             [{"reg_param": r} for r in (0.001, 0.01, 0.1, 0.3)])],
        splitter=DataSplitter(reserve_test_fraction=0.1, seed=42))
    pred = lab.transform_with(sel, checked)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred).train())
    automl_s = time.time() - t0
    s = model.selector_summary()
    holdout = s.holdout_evaluation.get("binary classification", {})
    result["automl"] = {
        "wall_s": round(automl_s, 2),
        "holdout_auroc": round(float(holdout.get("au_roc", float("nan"))),
                               4),
        "best": s.best_model_name,
        "vector_width": None,
    }
    try:
        data = model.transform(frame)
        result["automl"]["vector_width"] = int(
            data.vector_meta(pred.origin_stage.input_names[1]).size)
    except Exception:
        pass
    result["value"] = result["automl"]["wall_s"]
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
