"""Online-serving microbench: micro-batched jit scoring vs the row closure.

Trains a small binary AutoML model (numeric + categorical features, GBT
candidate — the family the HIGGS-shape sweep selects), then drives the
SAME request stream three ways:

- ``row_path`` — ``model.score_function()`` called once per request: the
  reference-parity local closure (``OpWorkflowModelLocal`` semantics),
  python per stage + a 1-row jit dispatch for the model.
- ``scorer``   — ``serving.CompiledScorer.score_batch`` at ``max_batch``:
  the micro-batched jit engine itself. This is the apples-to-apples
  engine-vs-engine comparison the >=10x acceptance bar is asserted on
  (neither side includes queueing).
- ``server``   — the full ``serving.ScoringServer`` (bounded queue,
  futures, closed-loop feeder): the operational end-to-end number, which
  on a one-core CPU box is python-queue/GIL-bound between the scorer
  floor and the row path (recorded honestly alongside, with request
  latency percentiles).

Records best-of-``SERVING_TRIALS`` sustained throughput per path (single
samples on a shared box swing ~2x with scheduler noise; max-over-trials
compares steady states), p50/p95/p99 request latency, the batch-size
histogram, per-padding-bucket compile counts split warmup vs post-warmup
(the compile-cache contract: 0 after warmup), and row-vs-batch score
parity. Writes ``benchmarks/SERVING.json`` (atomic), prints one JSON line.

Platform honesty (PR 1's ``platform=='cpu'`` guard, extended): the
artifact records the measured backend verbatim; set
``SERVING_EXPECT_ACCEL=1`` to make a CPU fallback a hard error instead of
a silently mislabeled "accelerator" result.

Run: ``python benchmarks/bench_serving.py``. Knobs: SERVING_REQUESTS,
SERVING_MAX_BATCH, SERVING_TRAIN_ROWS, SERVING_SUBMITTERS.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

REQUESTS = int(os.environ.get("SERVING_REQUESTS", 4096))
ROW_REQUESTS = int(os.environ.get("SERVING_ROW_REQUESTS", 512))
MAX_BATCH = int(os.environ.get("SERVING_MAX_BATCH", 256))
TRAIN_ROWS = int(os.environ.get("SERVING_TRAIN_ROWS", 4000))
#: closed-loop feeder threads. Default 1: on a one-core CI box extra
#: submitters only contend with the batcher worker for the GIL and
#: depress the measured pipeline throughput (concurrency CORRECTNESS is
#: tests/test_serving.py's job); raise on real multi-core serving hosts
SUBMITTERS = int(os.environ.get("SERVING_SUBMITTERS", 1))
#: best-of-N trials per path: both measurements are ~0.3-0.7s samples on
#: a shared box, so single samples swing ~2x with machine noise; max over
#: trials compares steady states instead of scheduler luck
TRIALS = int(os.environ.get("SERVING_TRIALS", 3))
D_NUM = int(os.environ.get("SERVING_NUM_FEATURES", 16))
#: the served candidate: "gbt" (the family the HIGGS-shape AutoML sweep
#: selects — BASELINE best_model is a GBT) or "lr"
MODEL = os.environ.get("SERVING_MODEL", "gbt")


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_serving.py",
                "transmogrifai_tpu/serving/compiled.py",
                "transmogrifai_tpu/serving/batcher.py",
                "transmogrifai_tpu/serving/server.py",
                "transmogrifai_tpu/serving/metrics.py",
                "transmogrifai_tpu/dag.py",
                "transmogrifai_tpu/local/scoring.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train_model():
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.models.trees import OpGBTClassifier
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(7)
    n = TRAIN_ROWS
    X = rng.normal(size=(n, D_NUM))
    color = rng.choice(["red", "green", "blue", "teal"], size=n)
    logit = (1.3 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
             + 1.1 * (color == "red"))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
    cols = {"y": (ft.RealNN, y.tolist()),
            "color": (ft.PickList, color.tolist())}
    for j in range(D_NUM):
        cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
    frame = fr.HostFrame.from_dict(cols)
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify(
        [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
    candidate = (OpGBTClassifier(num_rounds=30, max_depth=3), [{}]) \
        if MODEL == "gbt" else \
        (OpLogisticRegression(max_iter=30), [{"reg_param": 0.01}])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[candidate])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    rows = []
    for i in range(max(REQUESTS, ROW_REQUESTS)):
        k = i % n
        row = {f"x{j}": float(X[k, j]) for j in range(D_NUM)}
        row["color"] = str(color[k])
        rows.append(row)
    return model, rows


def _pump(server, rows, results, start_evt, idx0, step):
    """One submitter thread: backpressure-respecting replay of its slice.
    On rejection it blocks on its OLDEST in-flight future (natural flow
    control: a client window, not a blind sleep)."""
    import collections

    from transmogrifai_tpu.serving import BackpressureError
    start_evt.wait()
    outstanding = collections.deque()
    i = idx0
    while i < len(rows):
        try:
            results[i] = server.submit(rows[i])
            outstanding.append(results[i])
            i += step
        except BackpressureError:
            if outstanding:
                # flow control only needs the slot back: an errored future
                # must not kill this submitter thread (the row's error is
                # reported at collection time), and a bounded wait keeps a
                # wedged server from hanging the bench forever
                try:
                    outstanding.popleft().result(timeout=300)
                except Exception:  # noqa: BLE001
                    pass
            else:
                time.sleep(0.001)


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import jax

    platform = jax.devices()[0].platform
    if os.environ.get("SERVING_EXPECT_ACCEL") == "1" and platform == "cpu":
        print(json.dumps({"metric": "online_serving_microbatch",
                          "error": "SERVING_EXPECT_ACCEL=1 but the backend "
                                   "initialized as cpu; refusing to record "
                                   "a CPU wall as an accelerator result"}))
        return 1

    from transmogrifai_tpu.serving import ScoringServer

    t0 = time.time()
    model, rows = _train_model()
    train_s = time.time() - t0
    print(f"# trained in {train_s:.1f}s on {platform}", file=sys.stderr)

    # -- row path: sequential closure calls (the pre-serving state of the
    # repo: one python fold per request), best of TRIALS ----------------
    score_fn = model.score_function()
    row_rows = rows[:ROW_REQUESTS]
    row_trials = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        row_scores = [score_fn(r) for r in row_rows]
        row_trials.append(
            round(len(row_rows) / (time.perf_counter() - t0), 1))
    row_rps = max(row_trials)
    print(f"# row path: {len(row_rows)} reqs x{TRIALS}, best "
          f"{row_rps:.0f} rps (trials {row_trials})", file=sys.stderr)

    # -- batched engine: CompiledScorer at max_batch, warmed ------------
    server = ScoringServer(model, max_batch=MAX_BATCH, max_wait_ms=2.0,
                           queue_capacity=4 * MAX_BATCH)
    counters = server.scorer.counters  # per-scorer compile attribution
    server.start(warmup_row=rows[0])
    warmup_compiles = counters.compiles_by_bucket()
    scorer_trials = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        for i in range(0, REQUESTS, MAX_BATCH):
            server.scorer.score_batch(rows[i:i + MAX_BATCH])
        scorer_trials.append(
            round(REQUESTS / (time.perf_counter() - t0), 1))
    scorer_rps = max(scorer_trials)
    print(f"# scorer (engine): {REQUESTS} reqs x{TRIALS} at batch "
          f"{MAX_BATCH}, best {scorer_rps:.0f} rps (trials "
          f"{scorer_trials})", file=sys.stderr)
    batched_trials = []
    batch_scores: list = []
    for _ in range(TRIALS):
        results: list = [None] * REQUESTS
        start_evt = threading.Event()
        threads = [threading.Thread(target=_pump, args=(
            server, rows[:REQUESTS], results, start_evt, k, SUBMITTERS))
            for k in range(SUBMITTERS)]
        for th in threads:
            th.start()
        t0 = time.perf_counter()
        start_evt.set()
        for th in threads:
            th.join()
        batch_scores = [f.result() for f in results]
        batched_trials.append(
            round(REQUESTS / (time.perf_counter() - t0), 1))
    server_rps = max(batched_trials)
    server.stop()
    total_compiles = counters.compiles_by_bucket()
    post_warmup = {b: total_compiles.get(b, 0) - warmup_compiles.get(b, 0)
                   for b in total_compiles}
    snap = server.snapshot()
    print(f"# server (end-to-end): {REQUESTS} reqs x{TRIALS}, best "
          f"{server_rps:.0f} rps (trials {batched_trials}), p50="
          f"{snap['latencyMs']['p50']}ms", file=sys.stderr)

    # -- parity + compile-cache assertions ------------------------------
    names = [f.name for f in model.result_features]
    parity = 0.0
    for e, g in zip(row_scores, batch_scores[:len(row_scores)]):
        for nm in names:
            ev, gv = e[nm], g[nm]
            if isinstance(ev, dict):
                parity = max(parity, max(
                    abs(float(ev[k]) - float(gv[k])) for k in ev))
            elif isinstance(ev, (list, tuple)):
                parity = max(parity, max(
                    (abs(a - b) for a, b in zip(ev, gv)), default=0.0))
    ok = True
    notes = []
    if any(v > 0 for v in post_warmup.values()):
        ok = False
        notes.append(f"compile-cache violation: post-warmup compiles "
                     f"{post_warmup}")
    if parity > 1e-4:
        ok = False
        notes.append(f"parity violation: max abs diff {parity}")
    if scorer_rps < 10 * row_rps:
        ok = False
        notes.append(f"engine speedup {scorer_rps / row_rps:.1f}x below "
                     "the 10x acceptance bar")

    artifact = {
        "metric": "online_serving_microbatch",
        "unit": "rps",
        "platform": platform,
        "requests": REQUESTS,
        "row_path_requests": len(row_rows),
        "max_batch": MAX_BATCH,
        "submitters": SUBMITTERS,
        "train_rows": TRAIN_ROWS,
        "model": MODEL,
        "num_features": D_NUM,
        "trials": TRIALS,
        "row_path_rps": row_rps,
        "row_path_trials_rps": row_trials,
        "scorer_rps": scorer_rps,
        "scorer_trials_rps": scorer_trials,
        "server_rps": server_rps,
        "server_trials_rps": batched_trials,
        "speedup": round(scorer_rps / row_rps, 2),
        "server_speedup": round(server_rps / row_rps, 2),
        "latency_ms": snap["latencyMs"],
        "batch_size_histogram": snap["batches"]["sizeHistogram"],
        "mean_batch_size": snap["batches"]["meanSize"],
        "buckets": [{"bucket": b,
                     "warmup_compiles": warmup_compiles.get(b, 0),
                     "post_warmup_compiles": post_warmup.get(b, 0)}
                    for b in sorted(total_compiles)],
        "degraded_batches": snap["batches"]["degraded"],
        "parity_max_abs_diff": parity,
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "SERVING.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
