"""Dispatch-watchdog + compile-telemetry hot-path overhead microbench.

Round 12 arms a stall deadline (``utils/devicewatch.py``) around every
blocking device wait — including EVERY serving batch dispatch — and
registers each dispatch in the in-flight ledger. This bench proves the
cost on the serving throughput path stays within the 2% acceptance
bound (``scripts/check_artifacts.py``, ``devicewatch_overhead``), and
that the one-sync sweep still costs exactly ONE blocking host sync with
the watchdog armed (the watchdog observes; it never syncs):

- ``base``    — the serving path with the watchdog DISABLED
  (``devicewatch.configure(enabled=False)``): guards no-op, no ledger.
- ``watched`` — the same path with the watchdog armed (generous stall
  deadline — a healthy run must never autopsy) and the compile-
  telemetry monitoring listener registered: the full round-12 cost —
  two ledger dict ops + one guard registration per BATCH, plus the
  monitor thread polling in the background.

Methodology is ``bench_tracing_overhead.py``'s (see its docstring for
why): fine-interleaved counterbalanced slices so both modes sample the
same machine states, gc frozen + paused across the timed region, median
over trials with the per-trial spread reported.

The artifact additionally carries the counter-asserted sweep leg: a
fold-stacked async CV sweep trained under the armed watchdog, whose
``SweepCounters.sweep_host_syncs`` must read exactly 1 (and 0 stalls
fired anywhere in the bench — ``false_stalls``).

Run: ``python benchmarks/bench_devicewatch_overhead.py``. Knobs:
DEVICEWATCH_REQUESTS, DEVICEWATCH_SLICE, DEVICEWATCH_MAX_BATCH,
DEVICEWATCH_TRAIN_ROWS, DEVICEWATCH_TRIALS.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

REQUESTS = int(os.environ.get("DEVICEWATCH_REQUESTS", 24576))
SLICE = int(os.environ.get("DEVICEWATCH_SLICE", 1024))
MAX_BATCH = int(os.environ.get("DEVICEWATCH_MAX_BATCH", 256))
TRAIN_ROWS = int(os.environ.get("DEVICEWATCH_TRAIN_ROWS", 2500))
TRIALS = int(os.environ.get("DEVICEWATCH_TRIALS", 7))
D_NUM = int(os.environ.get("DEVICEWATCH_NUM_FEATURES", 12))


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_devicewatch_overhead.py",
                "transmogrifai_tpu/utils/devicewatch.py",
                "transmogrifai_tpu/serving/server.py",
                "transmogrifai_tpu/serving/compiled.py",
                "transmogrifai_tpu/selector/model_selector.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train_model():
    import numpy as np

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(17)
    n = TRAIN_ROWS
    X = rng.normal(size=(n, D_NUM))
    logit = 1.4 * X[:, 0] - 0.9 * X[:, 1] + 0.5 * X[:, 2]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
    cols = {"y": (ft.RealNN, y.tolist())}
    for j in range(D_NUM):
        cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
    frame = fr.HostFrame.from_dict(cols)
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify([feats[f"x{j}"] for j in range(D_NUM)])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=25), [{"reg_param": 0.01}])])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    rows = [{f"x{j}": float(X[i % n, j]) for j in range(D_NUM)}
            for i in range(REQUESTS)]
    return model, rows


def _drive(server, rows) -> None:
    """One closed-loop leg (flow control = block on the oldest
    in-flight future at backpressure)."""
    import collections

    from transmogrifai_tpu.serving import BackpressureError

    outstanding = collections.deque()
    i = 0
    while i < len(rows):
        try:
            fut = server.submit(rows[i])
        except BackpressureError:
            if outstanding:
                try:
                    outstanding.popleft().result(timeout=300)
                except Exception:  # noqa: BLE001 — a row error reports at collection
                    pass
            continue
        outstanding.append(fut)
        i += 1
    for fut in outstanding:
        try:
            fut.result(timeout=300)
        except Exception:  # noqa: BLE001
            pass


def _sweep_one_sync_leg() -> dict:
    """The counter-asserted sweep leg: a fold-stacked ASYNC sweep under
    the armed watchdog must still settle behind exactly one blocking
    host sync (the guard observes the barrier; it never adds a sync)."""
    import numpy as np

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import (
        OpLinearSVC, OpLogisticRegression,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils import devicewatch
    from transmogrifai_tpu.utils.profiling import profiler, sweep_counters
    from transmogrifai_tpu.workflow import Workflow

    os.environ["TRANSMOGRIFAI_SWEEP_STACKED"] = "1"
    os.environ["TRANSMOGRIFAI_SWEEP_ASYNC"] = "1"
    profiler.reset(app_name="devicewatch_sweep")
    stalls_before = devicewatch.watchdog.stalls
    guards_before = devicewatch.watchdog.guards
    rng = np.random.default_rng(5)
    n = 2000
    x = rng.normal(size=n)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-1.5 * x))).astype(float)
    frame = fr.HostFrame.from_dict({
        "y": (ft.RealNN, y.tolist()),
        "x": (ft.Real, x.tolist()),
    })
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify([feats["x"]])
    sel = BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, seed=2, models_and_parameters=[
            (OpLogisticRegression(max_iter=15),
             [{"reg_param": r} for r in (0.01, 0.1)]),
            (OpLinearSVC(max_iter=15), [{"reg_param": 0.01}]),
        ])
    pred = feats["y"].transform_with(sel, features)
    (Workflow().set_input_frame(frame)
     .set_result_features(pred, features).train())
    run = sweep_counters.run_to_json()
    return {
        "host_syncs": run["sweepHostSyncs"],
        "async_families": run["asyncFamilies"],
        "families": 2,
        "watchdog_armed": bool(devicewatch.watchdog.enabled),
        "settle_guards_armed":
            devicewatch.watchdog.guards - guards_before,
        "stalls": devicewatch.watchdog.stalls - stalls_before,
    }


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import gc
    import statistics

    import jax

    from transmogrifai_tpu.serving import ScoringServer
    from transmogrifai_tpu.utils import devicewatch

    platform = jax.devices()[0].platform
    t0 = time.time()
    model, rows = _train_model()
    print(f"# trained in {time.time() - t0:.1f}s on {platform}",
          file=sys.stderr)

    # armed mode: generous deadline (a healthy dispatch is ms-scale —
    # any fire is a FALSE stall and fails the artifact), telemetry on
    devicewatch.configure(enabled=True, stall_timeout_s=600.0,
                          incident_dir=None)
    devicewatch.compile_telemetry.ensure_listener()
    stalls0 = devicewatch.watchdog.stalls
    guards0 = devicewatch.watchdog.guards

    server = ScoringServer(model, max_batch=MAX_BATCH, max_wait_ms=2.0,
                           queue_capacity=4 * MAX_BATCH)
    server.start(warmup_row=rows[0])

    # one throwaway leg per mode: jit/allocator warm state must not land
    # on whichever mode runs first
    devicewatch.configure(enabled=False)
    _drive(server, rows[:MAX_BATCH * 4])
    devicewatch.configure(enabled=True)
    _drive(server, rows[:MAX_BATCH * 4])
    gc.collect()
    gc.freeze()

    n_slices = max(REQUESTS // SLICE, 1)
    slice_rows = rows[:SLICE]
    base_trials: list = []
    watched_trials: list = []
    overheads: list = []
    for k in range(TRIALS):
        t_base = t_watched = 0.0
        gc.collect()
        gc.disable()
        for s in range(n_slices):
            for mode in (("base", "watched") if s % 2 == 0
                         else ("watched", "base")):
                devicewatch.configure(enabled=(mode == "watched"))
                s0 = time.perf_counter()
                _drive(server, slice_rows)
                dt = time.perf_counter() - s0
                if mode == "base":
                    t_base += dt
                else:
                    t_watched += dt
        gc.enable()
        base_trials.append(round(n_slices * SLICE / t_base, 1))
        watched_trials.append(round(n_slices * SLICE / t_watched, 1))
        overheads.append((t_watched - t_base) / t_base * 100.0)
        print(f"# trial {k}: base {base_trials[-1]:.0f} rps, watched "
              f"{watched_trials[-1]:.0f} rps, overhead "
              f"{overheads[-1]:+.2f}%", file=sys.stderr)
    server.stop()
    gc.unfreeze()
    devicewatch.configure(enabled=True)

    med = statistics.median(overheads)
    mid = min(range(len(overheads)),
              key=lambda i: abs(overheads[i] - med))
    overhead_pct = overheads[mid]
    base_rps = base_trials[mid]
    watched_rps = watched_trials[mid]
    guards_armed = devicewatch.watchdog.guards - guards0

    sweep = _sweep_one_sync_leg()
    false_stalls = devicewatch.watchdog.stalls - stalls0
    tele = devicewatch.compile_telemetry.to_json()

    ok = True
    notes = []
    if overhead_pct > 2.0:
        ok = False
        notes.append(f"devicewatch overhead {overhead_pct:.2f}% exceeds "
                     "the 2% acceptance bound")
    if guards_armed <= 0:
        ok = False
        notes.append("the watched legs armed no guards")
    if false_stalls != 0:
        ok = False
        notes.append(f"{false_stalls} false stall fire(s) on healthy "
                     "waits")
    if sweep["host_syncs"] != 1:
        ok = False
        notes.append(f"one-sync sweep recorded {sweep['host_syncs']} "
                     "blocking host syncs under the armed watchdog "
                     "(must be exactly 1)")

    artifact = {
        "metric": "devicewatch_overhead",
        "unit": "rps",
        "platform": platform,
        "requests": REQUESTS,
        "slice": SLICE,
        "max_batch": MAX_BATCH,
        "train_rows": TRAIN_ROWS,
        "trials": TRIALS,
        "base_rps": base_rps,
        "base_trials_rps": base_trials,
        "watched_rps": watched_rps,
        "watched_trials_rps": watched_trials,
        "overhead_pct": round(overhead_pct, 3),
        "overhead_trials_pct": [round(o, 2) for o in overheads],
        "guards_armed": int(guards_armed),
        "false_stalls": int(false_stalls),
        "sweep_one_sync": sweep,
        "compile_telemetry": {"programs": tele["programs"],
                              "wall_s": tele["wallSeconds"],
                              "slow": tele["slowCompiles"]},
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "DEVICEWATCH_OVERHEAD.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
