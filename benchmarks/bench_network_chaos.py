"""Network-chaos bench: the 1000-model fleet scored over the binary
wire THROUGH a deterministic TCP fault proxy, proving the exactly-once
retry contract under every network fault kind.

Topology (all real processes, real sockets)::

    8 client threads -> Router.dispatch -> ChaosProxy -> worker x2
                                            (per replica)

Two REAL ``scaleout.worker`` processes are spawned directly (the
supervisor is deliberately not used: it would re-point the router at
the workers' true ports and route AROUND the proxies). Each worker
lazily registers the same 1000-tenant symlink fan-out used by
``bench_multitenant_fleet.py``; every request is a binary columnar
frame carrying a stable ``X-Request-Id`` (also embedded in the frame
meta section), reused verbatim across every client-level retry — the
idempotency key the replica :class:`DedupeRing` answers duplicates
from.

Three legs:

1. **warm** — every model either measured leg will touch is scored
   once through plan-free proxies, so cold-start paging never pollutes
   the latency comparison (requests still count toward the
   exactly-once ledger).
2. **steady** — Zipf traffic through TRANSPARENT proxies: the baseline
   pays the same extra hop the chaos leg does.
3. **chaos** — fresh proxies sharing ONE seeded :class:`FaultPlan`
   that schedules all seven ``NET_KINDS``: isolated single-invocation
   ``reset`` windows (consecutive resets would defeat the router's
   bounded same-replica retry and spill an already-scored request to
   the other replica's ring), reply-side ``truncate``/``corrupt``
   windows that GUARANTEE dedupe hits (the reply dies after the ring
   cached it), low-probability ``delay``/``split`` noise, early
   ``refuse`` windows on the first upstream dials, and one ``blackhole``
   bounded by the router's 2 s upstream deadline.

The headline claim is the ledger: summed over both replicas,

    ``scored_total - distinct_requests == double_scores == 0``

every logical request was scored EXACTLY once, despite resets mid-reply
and client retries — the equality is the proof, enforced by
``scripts/check_artifacts.py::_validate_network_chaos`` together with
``zero_dropped``, all seven fault kinds fired, ``dedupe.hits >= 1``,
and chaos p99 <= 3x the same-run steady p99.

Hedging stays OFF here on purpose: a hedge duplicates a request id to
the ring *successor*, and per-replica rings would then count one
logical request as scored twice — the bench proves the retry path,
the hedge path is covered by tests/test_netchaos.py.

Run: ``python benchmarks/bench_network_chaos.py``. Knobs: NC_MODELS,
NC_REQUESTS (per measured leg), NC_CLIENTS.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

N_MODELS = int(os.environ.get("NC_MODELS", 1000))
REQUESTS = int(os.environ.get("NC_REQUESTS", 2000))
CLIENTS = int(os.environ.get("NC_CLIENTS", 8))
REPLICAS = 2
ZIPF_S = 1.3
TRAIN_ROWS = 400
D_NUM = 4
#: per-logical-request client deadline — a request that cannot settle
#: inside this is a DROP and fails the artifact
REQUEST_DEADLINE_S = 60.0
SPAWN_TIMEOUT_S = 240.0
HEARTBEAT_TTL_S = 8.0

#: the chaos leg's one plan. Every NET kind appears, each with a
#: deterministic single-invocation window (so all seven ALWAYS fire)
#: plus low-probability noise for delay/split. Resets are isolated
#: singles far apart: the router's same-replica retry (budget: one)
#: absorbs a lone reset; back-to-back resets on the same exchange
#: would spill the request — already scored and cached on replica A —
#: to replica B's independent ring, and the exactly-once ledger would
#: rightly fail.
CHAOS_PLAN = ";".join([
    "delay@net.read#10x1:0.01",      # deterministic: delay always fires
    "delay@net.read:0.008%0.005",    # ... plus sparse latency noise
    "split@net.write#50",            # deterministic short-read dribble
    "split@net.write%0.01",
    "refuse@net.connect#2",          # early: dials are scarce (~pool
    "refuse@net.connect#5",          # warm-up only, then keep-alive)
    "reset@net.write#30",            # mid-REPLY reset: scored+cached,
    "corrupt@net.write#120",         # reply corrupted after caching ->
                                     # client retry -> guaranteed ring hit
    "truncate@net.write#200",        # mid-frame reply truncation
    "reset@net.write#300",
    "truncate@net.write#700",
    "corrupt@net.read#60",           # request corrupted BEFORE scoring
    "corrupt@net.read#900",
    "reset@net.read#500",            # request killed before delivery
    "blackhole@net.read#999",        # swallowed request; the router's
                                     # 2s upstream deadline ends it
])
CHAOS_SEED = 20260807


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_network_chaos.py",
                "transmogrifai_tpu/utils/netchaos.py",
                "transmogrifai_tpu/utils/faults.py",
                "transmogrifai_tpu/scaleout/router.py",
                "transmogrifai_tpu/scaleout/wire.py",
                "transmogrifai_tpu/serving/aiohttp_core.py",
                "transmogrifai_tpu/serving/wireformat.py",
                "transmogrifai_tpu/serving/http.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train_canonical(root: str):
    """One tiny fitted binary workflow saved at ``root/canonical``;
    returns (checkpoint_path, request_rows)."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow

    UID.reset()
    rng = np.random.default_rng(3)
    n = TRAIN_ROWS
    X = rng.normal(size=(n, D_NUM))
    color = rng.choice(["red", "green", "blue"], size=n)
    logit = (1.3 * X[:, 0] - 0.8 * X[:, 1] + 1.1 * (color == "red"))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
    cols = {"y": (ft.RealNN, y.tolist()),
            "color": (ft.PickList, color.tolist())}
    for j in range(D_NUM):
        cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
    frame = fr.HostFrame.from_dict(cols)
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify(
        [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=25), [{}])])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    path = os.path.join(root, "canonical")
    model.save(path)
    rows = []
    for i in range(256):
        row = {f"x{j}": float(X[i, j]) for j in range(D_NUM)}
        row["color"] = str(color[i])
        rows.append(row)
    return path, rows


def _fan_out(fleet_root: str, canonical: str, n: int) -> list:
    ids = []
    names = os.listdir(canonical)
    for i in range(n):
        model_id = f"m{i:04d}"
        d = os.path.join(fleet_root, model_id, "v1")
        os.makedirs(d)
        for name in names:
            os.symlink(os.path.join(canonical, name),
                       os.path.join(d, name))
        ids.append(model_id)
    return ids


def _spawn_worker(state_dir: str, model_dir: str, replica_id: str,
                  log_dir: str) -> subprocess.Popen:
    """Spawn one REAL replica worker the way the supervisor does —
    module invocation, PYTHONPATH pinned to this checkout, own process
    group, log file — but WITHOUT a supervisor, so nothing ever
    re-points the router away from the chaos proxies."""
    cmd = [sys.executable, "-m", "transmogrifai_tpu.scaleout.worker",
           "--state-dir", state_dir, "--replica-id", replica_id,
           "--model-dir", model_dir,
           "--tenancy", "--tenant-rate", "0",
           "--max-batch", "16", "--heartbeat-interval", "0.5"]
    env = dict(os.environ)
    parts = [REPO] + [p for p in sys.path if p and p != REPO]
    env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
    log_fh = open(os.path.join(log_dir, f"{replica_id}.log"), "ab")
    try:
        return subprocess.Popen(cmd, env=env, stdout=log_fh,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
    finally:
        log_fh.close()


def _wait_ready(state_dir: str, want: list, procs: list) -> dict:
    """Block until every replica heartbeats fresh+ready; returns
    replica_id -> bound port."""
    from transmogrifai_tpu.scaleout import wire
    deadline = time.time() + SPAWN_TIMEOUT_S
    while time.time() < deadline:
        for p in procs:
            if p.poll() is not None:
                raise RuntimeError(
                    f"worker exited rc={p.returncode} during spawn")
        hbs = wire.read_heartbeats(state_dir)
        ready = {rid: doc for rid, doc in hbs.items()
                 if doc.get("state") == "ready"
                 and wire.is_fresh(doc, HEARTBEAT_TTL_S)}
        if all(rid in ready for rid in want):
            return {rid: int(ready[rid]["port"]) for rid in want}
        time.sleep(0.25)
    raise RuntimeError(f"workers not ready in {SPAWN_TIMEOUT_S}s")


def _pctl(samples: list, p: float) -> float:
    s = sorted(samples)
    i = min(int(p * (len(s) - 1) + 0.5), len(s) - 1)
    return round(s[i], 3)


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import numpy as np

    import jax

    platform = jax.devices()[0].platform

    from transmogrifai_tpu.scaleout import wire
    from transmogrifai_tpu.scaleout.router import Router
    from transmogrifai_tpu.serving.wireformat import (
        CONTENT_TYPE_FRAME,
        decode_frame,
        encode_rows,
    )
    from transmogrifai_tpu.utils.faults import FaultPlan
    from transmogrifai_tpu.utils.netchaos import ChaosProxy

    t_start = time.time()
    root = tempfile.mkdtemp(prefix="net_chaos_")
    canonical, rows = _train_canonical(root)
    fleet_root = os.path.join(root, "tenants")
    os.makedirs(fleet_root)
    ids = _fan_out(fleet_root, canonical, N_MODELS)
    print(f"# trained + fanned out {len(ids)} tenants in "
          f"{time.time() - t_start:.1f}s on {platform}", file=sys.stderr)

    state_dir = os.path.join(root, "state")
    rids = [f"r{i}" for i in range(REPLICAS)]
    procs = [_spawn_worker(state_dir, fleet_root, rid, root)
             for rid in rids]
    try:
        return _run(np, wire, Router, ChaosProxy, FaultPlan,
                    CONTENT_TYPE_FRAME, decode_frame, encode_rows,
                    platform, t_start, state_dir, rids, procs, ids,
                    rows, root)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _run(np, wire, Router, ChaosProxy, FaultPlan, CONTENT_TYPE_FRAME,
         decode_frame, encode_rows, platform, t_start, state_dir, rids,
         procs, ids, rows, root) -> int:
    t0 = time.time()
    ports = _wait_ready(state_dir, rids, procs)
    print(f"# {len(ports)} workers ready in {time.time() - t0:.1f}s: "
          f"{ports}", file=sys.stderr)

    # hedge=False: per-replica dedupe rings make a hedged duplicate a
    # legitimate second execution — the ledger would report it, loudly
    router = Router(upstream_timeout_s=2.0, retry_backoff_s=0.01)
    dropped = [0]
    issued = [0]
    lock = threading.Lock()

    def _point_at(proxies: dict) -> None:
        for rid, proxy in proxies.items():
            router.set_replica(rid, proxy.port)
            router.mark_up(rid)

    def _request(rid_tag: str, model_id: str, row: dict,
                 samples) -> None:
        """One LOGICAL request: a stable request id reused across every
        retry, settled only by a 200 whose reply frame decodes."""
        body = encode_rows(model_id, [row],
                           meta={"request_id": rid_tag})
        headers = {"Content-Type": CONTENT_TYPE_FRAME,
                   "X-Request-Id": rid_tag}
        with lock:
            issued[0] += 1
        t_req = time.perf_counter()
        deadline = t_req + REQUEST_DEADLINE_S
        while True:
            try:
                status, rh, payload, _rep = router.dispatch(
                    model_id, body, dict(headers))
            except Exception as e:  # noqa: BLE001 — retry, never crash a client
                status, rh, payload = 0, {}, repr(e).encode()
            if status == 200:
                try:
                    decode_frame(payload)
                    break  # settled — integrity-checked end to end
                except Exception:  # noqa: BLE001 — corrupted reply: retry, same id
                    pass
            if time.perf_counter() > deadline:
                with lock:
                    dropped[0] += 1
                print(f"# DROP {rid_tag} {model_id}: {status} "
                      f"{payload[:120]!r}", file=sys.stderr)
                return
            retry_after = None
            for k, v in (rh or {}).items():
                if k.lower() == "retry-after":
                    retry_after = v
            try:
                pause = min(float(retry_after), 0.25) \
                    if retry_after else 0.005
            except (TypeError, ValueError):
                pause = 0.005
            time.sleep(pause)
        if samples is not None:
            samples.append((time.perf_counter() - t_req) * 1e3)

    def _leg(tag: str, reqs: list, samples) -> float:
        cursor = [0]

        def _worker():
            while True:
                with lock:
                    i = cursor[0]
                    if i >= len(reqs):
                        return
                    cursor[0] = i + 1
                model_id, row_i = reqs[i]
                _request(f"{tag}-{i:06d}", model_id,
                         rows[row_i], samples)

        t_leg = time.time()
        threads = [threading.Thread(target=_worker, daemon=True)
                   for _ in range(CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.time() - t_leg

    rng = np.random.default_rng(7)
    steady_reqs = [
        (ids[int(r)], i % len(rows)) for i, r in enumerate(
            np.minimum(rng.zipf(ZIPF_S, size=REQUESTS), N_MODELS) - 1)]
    chaos_reqs = [
        (ids[int(r)], i % len(rows)) for i, r in enumerate(
            np.minimum(rng.zipf(ZIPF_S, size=REQUESTS), N_MODELS) - 1)]

    # -- leg 1: warm every tenant either measured leg touches ---------------
    quiet = FaultPlan.parse("")     # explicit: immune to env plans
    warm_proxies = {rid: ChaosProxy(ports[rid], plan=quiet,
                                    name=f"warm-{rid}").start()
                    for rid in rids}
    _point_at(warm_proxies)
    touched = sorted({m for m, _ in steady_reqs + chaos_reqs})
    warm_reqs = [(m, i % len(rows)) for i, m in enumerate(touched)]
    wall = _leg("warm", warm_reqs, None)
    print(f"# warm: {len(warm_reqs)} tenants paged in through the "
          f"proxy hop in {wall:.1f}s", file=sys.stderr)
    for proxy in warm_proxies.values():
        proxy.stop()

    # -- leg 2: steady baseline through transparent proxies -----------------
    steady_proxies = {rid: ChaosProxy(ports[rid], plan=quiet,
                                      name=f"steady-{rid}").start()
                      for rid in rids}
    _point_at(steady_proxies)
    steady_samples: list = []
    steady_wall = _leg("steady", steady_reqs, steady_samples)
    steady_rps = len(steady_samples) / max(steady_wall, 1e-9)
    print(f"# steady: {len(steady_samples)} requests, "
          f"{steady_rps:.0f} rps, p99 {_pctl(steady_samples, 0.99)}ms",
          file=sys.stderr)
    for proxy in steady_proxies.values():
        proxy.stop()

    # -- leg 3: chaos — same traffic shape, every fault kind ----------------
    plan = FaultPlan.parse(CHAOS_PLAN, seed=CHAOS_SEED)
    chaos_proxies = {rid: ChaosProxy(ports[rid], plan=plan,
                                     name=f"chaos-{rid}").start()
                     for rid in rids}
    _point_at(chaos_proxies)
    chaos_samples: list = []
    chaos_wall = _leg("chaos", chaos_reqs, chaos_samples)
    chaos_rps = len(chaos_samples) / max(chaos_wall, 1e-9)
    for proxy in chaos_proxies.values():
        proxy.stop()               # frees any parked blackhole thread

    fault_counts: dict = {}
    for _site, _inv, kind in plan.fired:
        fault_counts[kind] = fault_counts.get(kind, 0) + 1
    print(f"# chaos: {len(chaos_samples)} requests, "
          f"{chaos_rps:.0f} rps, p99 {_pctl(chaos_samples, 0.99)}ms, "
          f"faults fired {fault_counts}", file=sys.stderr)

    # -- the exactly-once ledger (control plane, NOT via proxies) -----------
    models_seen = set()
    scored_total = hits = waits = 0
    router_doc = router.metrics.to_json()
    for rid in rids:
        st = wire.admin_call(ports[rid], "status", timeout_s=30)
        models_seen.add(len(st.get("models", [])))
        dd = st.get("dedupe") or {}
        scored_total += int(dd.get("scored", 0))
        hits += int(dd.get("hits", 0))
        waits += int(dd.get("waits", 0))
    distinct = int(issued[0])
    double_scores = scored_total - distinct
    zero_dropped = dropped[0] == 0
    steady_p99 = _pctl(steady_samples, 0.99)
    chaos_p99 = _pctl(chaos_samples, 0.99)
    inflation = round(chaos_p99 / max(steady_p99, 1e-9), 3)
    print(f"# ledger: {distinct} distinct requests, {scored_total} "
          f"scored, {double_scores} double, dedupe hits={hits} "
          f"waits={waits}; router {router_doc.get('resets', 0)} resets "
          f"{router_doc.get('refusals', 0)} refusals "
          f"{router_doc.get('retries', 0)} retries", file=sys.stderr)

    from scripts.check_artifacts import _validate_network_chaos

    artifact = {
        "metric": "network_chaos",
        "platform": platform,
        "requests": int(distinct),
        "models": int(min(models_seen) if models_seen else 0),
        "wall_s": round(time.time() - t_start, 3),
        "zero_dropped": zero_dropped,
        "distinct_requests": distinct,
        "scored_total": int(scored_total),
        "double_scores": int(double_scores),
        "steady": {
            "rps": round(steady_rps, 1),
            "p50_ms": _pctl(steady_samples, 0.50),
            "p99_ms": steady_p99,
        },
        "chaos": {
            "rps": round(chaos_rps, 1),
            "p50_ms": _pctl(chaos_samples, 0.50),
            "p99_ms": chaos_p99,
        },
        "p99_inflation_x": inflation,
        "faults": fault_counts,
        "dedupe": {"hits": int(hits), "waits": int(waits)},
        "router": router_doc,
        "plan": CHAOS_PLAN,
        "plan_seed": CHAOS_SEED,
        "replicas": REPLICAS,
        "clients": CLIENTS,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    errors = _validate_network_chaos(artifact)
    artifact["ok"] = not errors
    artifact["notes"] = errors

    out_path = os.path.join(HERE, "NETWORK_CHAOS.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
