"""Pallas histogram kernel vs XLA scatter — the on-chip decision microbench.

``ops/histogram_pallas.py`` holds two implementations of the tree
learner's hot op (per-level (node, feature, bin) grad/hess histograms):
the compare+matmul Pallas kernel (MXU-friendly, limited to
node*bin <= 512 by its 8-sublane VMEM one-hot tile) and the XLA
scatter-add. This bench times BOTH standalone across the real level
shapes a depth-12 tree visits (1 -> 4096 nodes) at ``HIST_ROWS`` rows x 28
features x 64 bins, with block_until_ready fences and median-of-repeats,
and writes ``benchmarks/PALLAS_HIST.json`` — the committed artifact behind
the keep-or-delete decision the round-2 review asked for.

Run on the chip: ``python benchmarks/bench_pallas_hist.py``
(CPU runs measure the interpret path and are labeled as such).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

ROWS = int(os.environ.get("HIST_ROWS", 1_000_000))
D = 28
BINS = 64
NODE_COUNTS = [1, 2, 4, 8, 16, 64, 256, 1024, 4096]
REPEATS = int(os.environ.get("HIST_REPEATS", 5))


def _median_time(fn, variant_args, **kw):
    """Host-fetch-fenced median over fresh node-vector variants.

    block_until_ready is NOT a real fence on the axon backend (see
    benchmarks/_timing.py), so each repeat fetches a scalar of the
    result and uses a node vector that has not executed before.
    """
    from _timing import fence
    fence(fn(*variant_args[0], **kw)[0])   # compile + warm
    times = []
    for i in range(REPEATS):
        args = variant_args[1 + i % (len(variant_args) - 1)]
        t0 = time.perf_counter()
        fence(fn(*args, **kw)[0])
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> int:
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax
        try:
            jax.config.update("jax_platforms", want)
        except RuntimeError:
            pass
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.ops.histogram_pallas import (
        node_bin_histogram, node_bin_histogram_xla,
    )

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    Xb = jnp.asarray(rng.integers(0, BINS, size=(ROWS, D)), jnp.int32)
    grad = jnp.asarray(rng.normal(size=ROWS), jnp.float32)
    hess = jnp.asarray(rng.uniform(0.2, 1.0, size=ROWS), jnp.float32)

    results = []
    for n_nodes in NODE_COUNTS:
        variants = [
            (Xb, jnp.asarray(rng.integers(0, n_nodes, size=ROWS), jnp.int32),
             grad, hess) for _ in range(REPEATS + 1)]
        t_xla = _median_time(node_bin_histogram_xla, variants,
                             n_nodes=n_nodes, n_bins=BINS)
        row = {"nodes": n_nodes, "xla_scatter_ms": round(t_xla * 1e3, 3)}
        # the kernel only lowers while the one-hot tile fits VMEM
        # (node_bin_histogram itself falls back beyond that — time the
        # kernel only where it genuinely runs)
        from transmogrifai_tpu.ops.histogram_pallas import (
            _CHUNK, _EQ_BUDGET,
        )
        lowers = n_nodes * BINS * _CHUNK * 4 * 8 <= _EQ_BUDGET
        if lowers:
            t_pal = _median_time(node_bin_histogram, variants,
                                 n_nodes=n_nodes, n_bins=BINS)
            row["pallas_ms"] = round(t_pal * 1e3, 3)
            row["pallas_speedup"] = round(t_xla / t_pal, 2)
        else:
            row["pallas_ms"] = None
            row["note"] = "beyond the kernel's VMEM one-hot tile cap"
        results.append(row)
        print(f"# {row}", file=sys.stderr)

    artifact = {
        "metric": "node_bin_histogram_microbench",
        "rows": ROWS, "features": D, "bins": BINS,
        "platform": platform,
        "interpret_mode": platform != "tpu",
        "repeats": REPEATS,
        "levels": results,
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "PALLAS_HIST.json")
    with open(out_path, "w") as fh:
        json.dump(artifact, fh, indent=2)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    sys.exit(main())
