"""Multi-process serving scale-out load test: N replica fleet workers
behind the consistent-hash router, vs the same models in ONE fleet
process — with a mid-run replica kill -9 and a rolling promotion.

Topology: the MAIN process trains ``SCALEOUT_MODELS`` small binary
AutoML models (one endpoint id each, versioned layout; one id gets a
v2 candidate for the roll), then measures two legs with the same
client fleet (separate OS processes, persistent connections,
closed-loop round-robin over the model ids):

1. **single-fleet baseline**: one replica worker process serving every
   model directly (the PR 6 shape, matched load) -> ``single_fleet``
   rps/p99. This leg also publishes the shared program-artifact
   manifests and populates the shared XLA compilation cache, so leg 2
   proves the map-everywhere path.
2. **scale-out**: ``SCALEOUT_REPLICAS`` workers behind the router. At
   ~35% a victim replica takes ``kill -9`` (the router must absorb it
   as retries — zero client-visible drops — and the supervisor must
   respawn it); at ~65% a rolling promotion moves one model to v2
   across every replica (zero global downtime: no half-second bucket
   of the roll window goes successless).

Committed to ``benchmarks/SERVING_SCALEOUT.json`` (schema-gated in
tier-1 by ``scripts/check_artifacts.py``): aggregate rps + p99 vs the
matched-load single-fleet leg (``scale_ratio`` — measured on THIS
host; ``host_cpus`` is recorded because the ratio's ceiling is the
core count: replicas can't out-run the machine), the kill block's
zero-drop proof, the roll block's zero-downtime + fleet-convergence
proof, and the artifact block's 0-post-warmup-compiles bound on
replicas that mapped the shared artifacts.

Platform honesty: the artifact records the measured backend verbatim;
``SCALEOUT_EXPECT_ACCEL=1`` makes a CPU fallback a hard error.

Run: ``python benchmarks/bench_serving_scaleout.py``. Knobs:
SCALEOUT_REPLICAS, SCALEOUT_CLIENTS, SCALEOUT_DURATION_S,
SCALEOUT_BASELINE_S, SCALEOUT_TRAIN_ROWS, SCALEOUT_MAX_BATCH.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import multiprocessing
import os
import signal
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

REPLICAS = int(os.environ.get("SCALEOUT_REPLICAS", 4))
CLIENTS = int(os.environ.get("SCALEOUT_CLIENTS", 8))
DURATION_S = float(os.environ.get("SCALEOUT_DURATION_S", 24.0))
BASELINE_S = float(os.environ.get("SCALEOUT_BASELINE_S", 10.0))
TRAIN_ROWS = int(os.environ.get("SCALEOUT_TRAIN_ROWS", 1000))
MAX_BATCH = int(os.environ.get("SCALEOUT_MAX_BATCH", 32))
N_MODELS = int(os.environ.get("SCALEOUT_MODELS", 4))
KILL_AT = 0.35      # fraction of the scale-out leg
ROLL_AT = 0.65
ROLL_MODEL_IDX = 1  # which model id carries the v2 candidate
D_NUM = 8


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_serving_scaleout.py",
                "transmogrifai_tpu/scaleout/router.py",
                "transmogrifai_tpu/scaleout/worker.py",
                "transmogrifai_tpu/scaleout/supervisor.py",
                "transmogrifai_tpu/scaleout/artifacts.py",
                "transmogrifai_tpu/serving/fleet.py",
                "transmogrifai_tpu/serving/http.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _client(idx: int, port: int, rows_by_model: dict, end_at: float,
            out_q) -> None:
    """One load-generator PROCESS against ONE port (router or direct
    replica): closed-loop round-robin over the model ids on a
    persistent connection. 503 waits out Retry-After and repeats the
    slot (shed, not dropped); a transport error reconnects and repeats
    (the ROUTER owns replica deaths; the router itself never
    restarts). Records (t_done, latency_ms, ok)."""
    import http.client
    import json as _json
    models = sorted(rows_by_model)
    samples = []            # (t_done_epoch, latency_ms, ok)
    sent = got = errors = backpressure = reconnects = 0
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    i = idx
    while time.time() < end_at:
        model = models[i % len(models)]
        rows = rows_by_model[model]
        body = _json.dumps(rows[i % len(rows)])
        t0 = time.perf_counter()
        try:
            conn.request("POST", f"/score/{model}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
        except Exception:  # noqa: BLE001 — reconnect, repeat the slot
            conn.close()
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            reconnects += 1
            continue
        sent += 1
        if resp.status == 503:
            backpressure += 1
            time.sleep(min(float(resp.headers.get("Retry-After", 0.01)),
                           0.25))
            continue
        latency_ms = (time.perf_counter() - t0) * 1e3
        ok = resp.status == 200 and bool(payload)
        if ok:
            got += 1
        else:
            errors += 1
        samples.append((time.time(), round(latency_ms, 3), ok))
        i += 1
    conn.close()
    out_q.put({"idx": idx, "sent": sent, "got": got, "errors": errors,
               "backpressure": backpressure, "reconnects": reconnects,
               "samples": samples})


def _train_zoo(root: str) -> dict:
    """N_MODELS versioned endpoints + a v2 candidate for the roll
    target. Returns request rows per model id."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow

    def train(seed: int, max_iter: int = 25):
        UID.reset()   # versions of one endpoint share result names
        rng = np.random.default_rng(seed)
        n = TRAIN_ROWS
        X = rng.normal(size=(n, D_NUM))
        color = rng.choice(["red", "green", "blue"], size=n)
        logit = (1.3 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2]
                 + 1.1 * (color == "red"))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
        cols = {"y": (ft.RealNN, y.tolist()),
                "color": (ft.PickList, color.tolist())}
        for j in range(D_NUM):
            cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
        frame = fr.HostFrame.from_dict(cols)
        feats = FeatureBuilder.from_frame(frame, response="y")
        features = transmogrify(
            [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
        sel = BinaryClassificationModelSelector \
            .with_train_validation_split(
                seed=1, models_and_parameters=[
                    (OpLogisticRegression(max_iter=max_iter), [{}])])
        pred = feats["y"].transform_with(sel, features)
        model = (Workflow().set_input_frame(frame)
                 .set_result_features(pred, features).train())
        rows = []
        for i in range(256):
            k = i % n
            row = {f"x{j}": float(X[k, j]) for j in range(D_NUM)}
            row["color"] = str(color[k])
            rows.append(row)
        return model, rows

    rows_by_model = {}
    for i in range(N_MODELS):
        mid = f"m{i}"
        model, rows = train(seed=3 + 2 * i)
        model.save(os.path.join(root, mid, "v1"))
        if i == ROLL_MODEL_IDX:
            v2, _ = train(seed=3 + 2 * i, max_iter=26)
            v2.save(os.path.join(root, mid, "v2"))
        rows_by_model[mid] = rows
    return rows_by_model


def _drive(port: int, rows_by_model: dict, duration_s: float,
           n_clients: int) -> tuple:
    """Run the client fleet against ``port``; returns (results list,
    end window (t_start, t_end))."""
    ctx = multiprocessing.get_context("spawn")
    out_q = ctx.Queue()
    t_start = time.time()
    end_at = t_start + duration_s
    procs = [ctx.Process(target=_client,
                         args=(i, port, rows_by_model, end_at, out_q),
                         daemon=True)
             for i in range(n_clients)]
    for p in procs:
        p.start()
    results = [out_q.get(timeout=duration_s + 180) for _ in procs]
    for p in procs:
        p.join(timeout=30)
    return results, (t_start, end_at)


def _percentiles(samples, lo=None, hi=None):
    import numpy as np
    sel = [(t, lat) for t, lat, ok in samples if ok
           and (lo is None or t >= lo) and (hi is None or t <= hi)]
    if not sel:
        return None, None, 0
    lat = np.array([s[1] for s in sel])
    return (round(float(np.percentile(lat, 50)), 3),
            round(float(np.percentile(lat, 99)), 3), len(sel))


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import tempfile

    import jax

    platform = jax.devices()[0].platform
    if os.environ.get("SCALEOUT_EXPECT_ACCEL") == "1" \
            and platform == "cpu":
        print(json.dumps({"metric": "serving_scaleout",
                          "error": "SCALEOUT_EXPECT_ACCEL=1 but the "
                                   "backend initialized as cpu"}))
        return 1

    from transmogrifai_tpu.scaleout import wire
    from transmogrifai_tpu.scaleout.stack import ScaleoutStack

    t0 = time.time()
    root = tempfile.mkdtemp(prefix="scaleout_zoo_")
    rows_by_model = _train_zoo(root)
    roll_model = f"m{ROLL_MODEL_IDX}"
    print(f"# trained {N_MODELS} models (+1 candidate) in "
          f"{time.time() - t0:.1f}s on {platform}", file=sys.stderr)
    warm_rows = {mid: rows[0] for mid, rows in rows_by_model.items()}
    worker_args = ["--max-batch", str(MAX_BATCH),
                   "--queue-capacity", str(4 * MAX_BATCH),
                   "--heartbeat-interval", "0.5"]
    # keep each worker's XLA runtime single-threaded (BOTH legs, same
    # fairness): N replicas on a small host must not each spin a
    # core-count thread pool and thrash the scheduler
    worker_env = {"XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                                " --xla_cpu_multi_thread_eigen=false"
                                ).strip(),
                  "OMP_NUM_THREADS": "1"}

    # -- leg 1: single-fleet baseline (one worker, direct) --------------
    base_state = tempfile.mkdtemp(prefix="scaleout_base_")
    base = ScaleoutStack(root, base_state, replicas=1,
                         warm_rows=warm_rows, worker_args=worker_args,
                         worker_env=worker_env, heartbeat_ttl_s=4.0)
    base.start()
    hb = wire.read_heartbeats(base_state)
    base_port = next(iter(hb.values()))["port"]
    print(f"# baseline fleet worker on :{base_port}", file=sys.stderr)
    base_results, _ = _drive(base_port, rows_by_model, BASELINE_S,
                             CLIENTS)
    base.stop()
    base_got = sum(r["got"] for r in base_results)
    base_samples = [s for r in base_results for s in r["samples"]]
    base_wall = (max(s[0] for s in base_samples)
                 - min(s[0] for s in base_samples)) if base_samples \
        else BASELINE_S
    base_rps = base_got / max(base_wall, 1e-9)
    base_p50, base_p99, _ = _percentiles(base_samples)
    print(f"# single fleet: {base_rps:.0f} rps p50={base_p50}ms "
          f"p99={base_p99}ms", file=sys.stderr)

    # -- leg 2: scale-out (router + N replicas, kill + roll) ------------
    state = tempfile.mkdtemp(prefix="scaleout_state_")
    stack = ScaleoutStack(root, state, replicas=REPLICAS,
                          warm_rows=warm_rows,
                          worker_args=worker_args,
                          worker_env=worker_env, heartbeat_ttl_s=4.0)
    t_up = time.time()
    stack.start()
    print(f"# {REPLICAS} replicas up in {time.time() - t_up:.1f}s; "
          f"router :{stack.port}", file=sys.stderr)
    # artifact proof BEFORE traffic: every replica mapped the manifests
    mapped = {rid: hb.get("artifactMapped", [])
              for rid, hb in stack.supervisor.heartbeats().items()}

    import threading
    kill_doc: dict = {}
    roll_doc: dict = {}

    def chaos(t_start: float):
        # kill -9 the primary of the roll model (a replica that IS
        # taking traffic), then roll the model to v2
        time.sleep(max(t_start + KILL_AT * DURATION_S - time.time(), 0))
        victim = stack.router.ring.order(roll_model)[0]
        entry = stack.supervisor._procs.get(victim)
        kill_doc.update({"replica": victim, "atS": round(
            time.time() - t_start, 3)})
        if entry is not None:
            os.kill(entry.proc.pid, signal.SIGKILL)
        time.sleep(max(t_start + ROLL_AT * DURATION_S - time.time(), 0))
        roll_doc["window"] = [time.time(), None]
        try:
            rep = stack.rolling_swap(roll_model, version="v2",
                                     tolerance=2.0)
            roll_doc.update({"promoted": True,
                             "replicas": rep["replicas"],
                             "wallS": rep["wallSeconds"]})
        except Exception as e:  # noqa: BLE001 — recorded in the artifact
            roll_doc.update({"promoted": False,
                             "error": f"{type(e).__name__}: {e}"})
        roll_doc["window"][1] = time.time()

    t_start = time.time()
    chaos_thread = threading.Thread(target=chaos, args=(t_start,))
    chaos_thread.start()
    results, _ = _drive(stack.port, rows_by_model, DURATION_S, CLIENTS)
    chaos_thread.join(timeout=120)

    # post-run replica state (before stop)
    heartbeats = stack.supervisor.heartbeats()
    post_warmup_max = 0
    converged = True
    respawned = False
    statuses = {}
    for rid, hb in sorted(heartbeats.items()):
        try:
            st = wire.admin_call(hb["port"], "status", timeout_s=30)
        except wire.AdminError:
            continue
        statuses[rid] = {"artifactMapped": st.get("artifactMapped"),
                         "postWarmupCompiles":
                             st.get("postWarmupCompiles")}
        for per in (st.get("postWarmupCompiles") or {}).values():
            for n in per.values():
                post_warmup_max = max(post_warmup_max, int(n))
        active = {m["modelId"]: m["version"]
                  for m in st.get("models", []) if m.get("active")}
        if active.get(roll_model) != "v2":
            converged = False
    sup_doc = stack.supervisor.to_json()
    respawned = sup_doc["metrics"]["respawns"] >= 1
    router_doc = stack.router.metrics.to_json()
    store_doc = {}
    if stack.supervisor.model_dir:
        from transmogrifai_tpu.scaleout.artifacts import ArtifactStore
        store_doc = ArtifactStore(root).to_json()
    stack.stop()

    # -- aggregate -------------------------------------------------------
    import numpy as np
    sent = sum(r["sent"] for r in results)
    got = sum(r["got"] for r in results)
    errors = sum(r["errors"] for r in results)
    backpressure = sum(r["backpressure"] for r in results)
    reconnects = sum(r["reconnects"] for r in results)
    samples = [s for r in results for s in r["samples"]]
    if not samples or not roll_doc.get("window"):
        print(json.dumps({"metric": "serving_scaleout",
                          "error": "no samples or roll never ran"}))
        return 1
    t_done = np.array([s[0] for s in samples])
    wall = float(t_done.max() - t_done.min())
    aggregate_rps = got / max(wall, 1e-9)
    p50_full, p99_full, _ = _percentiles(samples)
    # the GATED p99 is steady state: the kill (+/-1s) and roll windows
    # are excluded — their cost is judged by the zero-drop and
    # zero-downtime proofs, not smeared into the latency bound
    kill_t = t_done.min() + (kill_doc.get("atS") or 0)
    r0w, r1w = roll_doc["window"]
    steady = [s for s in samples
              if not (kill_t - 1.0 <= s[0] <= kill_t + 1.0)
              and not (r0w - 0.5 <= s[0] <= (r1w or r0w) + 0.5)]
    p50, p99, _ = _percentiles(steady)
    if p99 is None:
        p50, p99 = p50_full, p99_full

    # zero-downtime proof for the roll: every 0.5s bucket of the roll
    # window (padded 0.5s each side) has successful completions
    r0, r1 = roll_doc["window"]
    ok_t = np.array([s[0] for s in samples if s[2]])
    edges = np.arange(r0 - 0.5, (r1 or r0) + 1.0, 0.5)
    per_bucket, _ = np.histogram(ok_t, bins=edges)
    zero_downtime = bool(roll_doc.get("promoted")
                         and (per_bucket > 0).all())

    zero_dropped = bool(errors == 0 and got == sent - backpressure)
    mapped_replicas = sum(1 for rid, m in mapped.items() if m)
    scale_ratio = aggregate_rps / max(base_rps, 1e-9)

    ok = True
    notes = []
    if not zero_dropped:
        ok = False
        notes.append(f"drops: sent={sent} got={got} errors={errors} "
                     f"backpressure={backpressure}")
    if not (roll_doc.get("promoted") and converged and zero_downtime):
        ok = False
        notes.append(f"roll: {roll_doc} converged={converged} "
                     f"buckets={per_bucket.tolist()}")
    if not respawned:
        ok = False
        notes.append("killed replica was not respawned")
    if post_warmup_max > 0:
        ok = False
        notes.append(f"compile storm: post-warmup max {post_warmup_max}")

    artifact = {
        "metric": "serving_scaleout",
        "unit": "rps",
        "platform": platform,
        "host_cpus": os.cpu_count(),
        "replicas": REPLICAS,
        "clients": CLIENTS,
        "models": N_MODELS,
        "requests": int(got),
        "duration_s": round(wall, 3),
        "max_batch": MAX_BATCH,
        "train_rows": TRAIN_ROWS,
        "aggregate_rps": round(aggregate_rps, 1),
        "p50_ms": p50,
        "p99_ms": p99,
        "p50_full_ms": p50_full,
        "p99_full_ms": p99_full,
        "single_fleet": {
            "rps": round(base_rps, 1),
            "p50_ms": base_p50,
            "p99_ms": base_p99,
            "clients": CLIENTS,
            "requests": int(base_got),
        },
        "scale_ratio": round(scale_ratio, 3),
        "scale_gate_regime": (
            "unconstrained" if (os.cpu_count() or 1) >= REPLICAS + 2
            else "core_constrained"),
        "baseline_committed": {
            "rps": 436.2, "source": "benchmarks/SERVING_FLEET.json",
            "note": "the committed 2-client single-process headline; "
                    "scale_ratio above is measured at MATCHED load on "
                    "this host — its ceiling is host_cpus",
        },
        "zero_dropped": zero_dropped,
        "errors": int(errors),
        "backpressure_retries": int(backpressure),
        "client_reconnects": int(reconnects),
        "kill": {
            "replica": kill_doc.get("replica"),
            "at_s": kill_doc.get("atS"),
            "zero_dropped": zero_dropped,
            "router_retries": router_doc["retries"],
            "router_markdowns": router_doc["markdowns"],
            "respawned": respawned,
        },
        "roll": {
            "model": roll_model,
            "to_version": "v2",
            "promoted": bool(roll_doc.get("promoted")),
            "replicas": roll_doc.get("replicas"),
            "wall_s": roll_doc.get("wallS"),
            "zero_downtime": zero_downtime,
            "converged": converged,
            "success_buckets": per_bucket.tolist(),
        },
        "artifacts": {
            "mapped_replicas": mapped_replicas,
            "replicas_seen": len(mapped),
            "post_warmup_compiles_max": int(post_warmup_max),
            "store": store_doc,
            "per_replica": statuses,
        },
        "router": router_doc,
        "supervisor": sup_doc["metrics"],
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "SERVING_SCALEOUT.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
