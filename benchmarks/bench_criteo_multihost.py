"""Criteo-shaped MULTI-HOST end-to-end: 2 real OS processes join over DCN
(`parallel/distributed.py`, the Spark-executor/Rabit analog — SURVEY §2.7),
each ingests + encodes ITS OWN row partition, the partitions assemble into
one global row-sharded array over one global mesh
(``shard_global_rows``), and the LR grid sweep trains as a single SPMD
program spanning both processes. Scores are checked for parity against an
identical single-process run.

This drives the same seam as ``tests/test_distributed.py`` through the
Criteo e2e shape (VERDICT r4 item 6): per-process ingest -> global mesh ->
sharded sweep -> parity. CPU DCN here; on a TPU pod the identical program
rides ICI/DCN (the mesh/collective layer is backend-transparent).

Writes ``benchmarks/CRITEO_MULTIHOST.json`` and prints ONE JSON line.

Quick pass: ``CRITEO_MH_ROWS=20000 python benchmarks/bench_criteo_multihost.py``
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

#: global rows (split evenly across processes)
N_ROWS = int(os.environ.get("CRITEO_MH_ROWS", 200_000))
N_PROCS = int(os.environ.get("CRITEO_MH_PROCS", 2))
HASH_FEATURES = int(os.environ.get("CRITEO_MH_HASH", 32))
N_NUM, N_CAT = 13, 26
CARDS = [10, 100, 1000, 10_000]
GRID = [0.001, 0.01, 0.1, 0.3]


def _synth_global(n: int):
    """Deterministic Criteo-shaped data: every process regenerates the same
    global arrays and slices its own partition (a stand-in for per-host
    file partitions; generation is cheap relative to the sweep)."""
    import numpy as np
    rng = np.random.default_rng(0)
    nums = rng.normal(size=(n, N_NUM)).astype(np.float32)
    cat_codes = np.stack([rng.integers(0, CARDS[j % len(CARDS)], n)
                          for j in range(N_CAT)], axis=1)
    effect = np.linspace(-1.0, 1.0, 10)[cat_codes[:, 0] % 10]
    logits = (0.8 * nums[:, 0] - 0.5 * nums[:, 1]
              + 0.4 * np.tanh(nums[:, 2]) + effect)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return nums, cat_codes, y


def _featurize(nums, cat_codes):
    """Host-side encode: per-column token hashing into HASH_FEATURES slots
    (the high-cardinality Criteo path), numerics appended raw."""
    import numpy as np
    from transmogrifai_tpu.ops.vectorizers.hashing import hash_token
    n = nums.shape[0]
    blocks = []
    for j in range(N_CAT):
        card = CARDS[j % len(CARDS)]
        tab = np.zeros((card, HASH_FEATURES), np.float32)
        for v in range(card):
            tab[v, hash_token(f"c{j}_{v}", HASH_FEATURES)] += 1.0
        blocks.append(tab[cat_codes[:, j]])
    blocks.append(nums)
    return np.concatenate(blocks, axis=1)


def _sweep(X, y, w):
    """The LR grid as the framework trains it (vmapped stacked axis,
    candidate sharding over 'model' when a mesh is active)."""
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    lr = OpLogisticRegression(max_iter=50)
    grid = [{"reg_param": r} for r in GRID]
    models = lr.grid_fit_arrays(X, y, w, grid)
    scores = lr.grid_predict_scores(models, X)
    return scores


def _auroc(scores, y):
    import numpy as np
    order = np.argsort(scores)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    n_pos = float((y > 0.5).sum())
    n_neg = float(len(y) - n_pos)
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[y > 0.5].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))


def _worker_main(pid: int, port: str) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2"
                               ).strip()
    sys.path.insert(0, REPO)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.experimental import multihost_utils

    from transmogrifai_tpu.parallel import distributed as D
    from transmogrifai_tpu.parallel import use_mesh

    D.initialize(coordinator_address=f"127.0.0.1:{port}",
                 num_processes=N_PROCS, process_id=pid)
    assert D.is_multi_process()
    ctx = D.global_mesh()

    per = N_ROWS // N_PROCS
    lo, hi = pid * per, (pid + 1) * per
    t0 = time.time()
    nums, cat_codes, y = _synth_global(N_ROWS)
    X_local = _featurize(nums[lo:hi], cat_codes[lo:hi])  # own partition only
    ingest_s = time.time() - t0

    t0 = time.time()
    Xg = D.shard_global_rows(ctx, X_local)
    yg = D.shard_global_rows(ctx, y[lo:hi])
    wg = D.shard_global_rows(ctx, np.ones(per, np.float32))
    assert Xg.shape[0] == per * N_PROCS  # one logical array, all processes
    with use_mesh(ctx):
        scores = _sweep(Xg, yg, wg)
        scores = jax.block_until_ready(scores)
    sweep_s = time.time() - t0

    # pull the global scores to every host for the parity check
    scores_np = np.asarray(multihost_utils.process_allgather(
        scores, tiled=True)) if scores.ndim else None
    aurocs = [_auroc(scores_np[g], y[: per * N_PROCS]) for g in
              range(len(GRID))]
    D.barrier()
    print("WORKER_RESULT " + json.dumps({
        "pid": pid, "local_rows": int(per), "global_rows": int(Xg.shape[0]),
        "n_processes": int(D.process_count()),
        "global_devices": int(len(jax.devices())),
        "mesh": {"data": int(ctx.n_data), "model": int(ctx.n_model)},
        "ingest_s": round(ingest_s, 2), "sweep_s": round(sweep_s, 2),
        "auroc_per_candidate": [round(a, 6) for a in aurocs],
    }), flush=True)


def _single_main() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, REPO)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    per = N_ROWS // N_PROCS
    n = per * N_PROCS
    nums, cat_codes, y = _synth_global(N_ROWS)
    X = _featurize(nums[:n], cat_codes[:n])
    t0 = time.time()
    scores = np.asarray(jax.block_until_ready(
        _sweep(X, y[:n], np.ones(n, np.float32))))
    sweep_s = time.time() - t0
    aurocs = [_auroc(scores[g], y[:n]) for g in range(len(GRID))]
    print("SINGLE_RESULT " + json.dumps({
        "sweep_s": round(sweep_s, 2),
        "auroc_per_candidate": [round(a, 6) for a in aurocs],
    }), flush=True)


def main() -> int:
    if os.environ.get("_CRITEO_MH_ROLE") == "worker":
        _worker_main(int(os.environ["_CRITEO_MH_PID"]),
                     os.environ["_CRITEO_MH_PORT"])
        return 0
    if os.environ.get("_CRITEO_MH_ROLE") == "single":
        _single_main()
        return 0

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base_env = {k: v for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    t0 = time.time()
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env={**base_env, "_CRITEO_MH_ROLE": "worker",
             "_CRITEO_MH_PID": str(i), "_CRITEO_MH_PORT": str(port)},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(N_PROCS)]
    workers = []
    for p in procs:
        out, err = p.communicate(timeout=900)
        if p.returncode != 0:
            print(json.dumps({"metric": "criteo_multihost", "ok": False,
                              "error": err.strip().splitlines()[-3:]}))
            return 1
        for line in out.splitlines():
            if line.startswith("WORKER_RESULT "):
                workers.append(json.loads(line[len("WORKER_RESULT "):]))
    multi_wall = time.time() - t0

    sp = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env={**base_env, "_CRITEO_MH_ROLE": "single"},
        capture_output=True, text=True, timeout=900)
    single = None
    for line in sp.stdout.splitlines():
        if line.startswith("SINGLE_RESULT "):
            single = json.loads(line[len("SINGLE_RESULT "):])

    parity = None
    if single and workers:
        a = workers[0]["auroc_per_candidate"]
        b = single["auroc_per_candidate"]
        parity = max(abs(x - z) for x, z in zip(a, b))

    result = {
        "metric": "criteo_multihost_e2e", "unit": "s",
        "platform": "cpu",  # this bench forces the CPU-virtual mesh
        "value": round(multi_wall, 2),
        "rows": N_ROWS, "hash_features": HASH_FEATURES,
        "workers": workers, "single_process": single,
        "auroc_parity_max_abs": parity,
        "ok": bool(workers
                   and all(w["n_processes"] == N_PROCS for w in workers)
                   and parity is not None and parity < 1e-3),
    }
    with open(os.path.join(HERE, "CRITEO_MULTIHOST.json"), "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
