"""HIGGS-11M-scale single-chip tree-fit probe (host-fetch fenced).

The north star (BASELINE.json) is the full AutoML pipeline on HIGGS-11M
on a v5e-8; this rig exposes ONE chip, so the headline bench runs at 4M
(bench.py). This probe supplies the scale evidence the curve cannot:
one OpGBTClassifier (50 rounds, depth 6) and one OpRandomForestClassifier
(50 trees, depth 12) fit at HIGGS row count x 28 features on the single
chip, through the real estimator surface (auto-selected sorted engine,
chunked ingest). Writes ``benchmarks/HIGGS11M_TREES.json``.

Run: python benchmarks/bench_higgs11m_trees.py  (HIGGS_ROWS overrides)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

ROWS = int(os.environ.get("HIGGS_ROWS", 11_000_000))
D = 28


def main() -> int:
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.models.trees import (
        OpGBTClassifier, OpRandomForestClassifier,
    )
    from transmogrifai_tpu.pipeline_data import _upload_rows

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    X = rng.normal(size=(ROWS, D)).astype(np.float32)
    logits = (1.2 * X[:, 0] - 0.7 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
              + 0.8 * np.sin(X[:, 4]))
    y = (rng.uniform(size=ROWS) < 1.0 / (1.0 + np.exp(-logits))
         ).astype(np.float64)

    from _timing import fence

    t0 = time.time()
    Xj = _upload_rows(X)          # chunked transfer (the 4M crash fix)
    yj = _upload_rows(y)
    w = jnp.ones(ROWS)
    fence(Xj)                     # host-fetch: block_until_ready is not
    fence(yj)                     # a real fence on axon (_timing.py)
    upload_s = time.time() - t0

    results = {"metric": "higgs11m_single_chip_tree_fits", "rows": ROWS,
               "features": D, "platform": platform,
               "upload_s": round(upload_s, 1),
               "fencing": "host scalar fetch", "fits": []}
    for est, label in ((OpGBTClassifier(num_rounds=50, max_depth=6),
                        "gbt_50x_d6"),
                       (OpRandomForestClassifier(num_trees=50, max_depth=12),
                        "rf_50x_d12")):
        t0 = time.time()
        model = est.fit_arrays(Xj, yj, w, est.params)
        fence(model.trees[2])     # fitted-scalar fetch completes the fit
        wall = time.time() - t0
        results["fits"].append({"model": label,
                                "wall_s": round(wall, 1)})
        print(f"# {label}: {wall:.1f}s", file=sys.stderr)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "HIGGS11M_TREES.json")
    with open(out, "w") as fh:
        json.dump(results, fh, indent=2)
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
