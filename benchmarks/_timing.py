"""Shared device-timing helpers for the on-chip benches and diagnostics.

On the axon-tunneled TPU, ``jax.block_until_ready`` returns before the
computation has actually executed (measured: fresh-input 137-GFLOP
matmuls "complete" in 0.04 ms), so any wall built on it times dispatch,
not execution. Every timing here therefore fences by FETCHING a scalar
of the result to the host, which cannot complete until the device value
exists. Callers should also pass ``variants`` — a list of distinct input
tuples longer than ``repeats`` — so a hypothetical remote result cache
can never serve a timed repeat.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["fence", "med_fetch"]


def fence(x) -> float:
    """Force completion by pulling one scalar of ``x`` to the host."""
    return float(np.asarray(x).ravel()[0])


def med_fetch(fn, variants, repeats: int = 3) -> float:
    """Median host-fenced wall of ``fn(*args)`` over fresh-input repeats.

    ``variants``: list of argument tuples. The first is burned on
    warmup/compile; timed repeats walk the remaining variants so no
    timed call reuses an input that has already executed (when
    ``len(variants) >= repeats + 1``, which callers should ensure).
    """
    fence(fn(*variants[0]))
    ts = []
    for i in range(repeats):
        args = variants[1 + i % (len(variants) - 1)] if len(variants) > 1 \
            else variants[0]
        t0 = time.perf_counter()
        fence(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))
