"""Resource-exhaustion resilience bench: injected OOMs mid-sweep and
mid-serving must cost a degradation rung, never the run.

Three fault-injected legs (deterministic ``oom`` kind, CPU — the whole
point of the harness is that no real TPU OOM is needed):

- **sweep**: a full AutoML ``train()`` (stacked LR family + stacked GBT
  depth-group, 3-fold CV) with ``oom@sweep.fit`` fired at the stacked
  dispatch. The degradation ladder re-dispatches the failing unit one
  rung down (per-fold loop / halved lane chunks); the artifact records
  run completion, the rung count, and ``winner_parity`` — the max abs
  winner train/validation metric delta vs the un-faulted run — within
  1e-5 (schema-asserted: a rung re-trains the same math at a smaller
  shape).
- **serving**: a warmed ``ScoringServer`` stream with
  ``oom@serving.dispatch`` fired mid-traffic. The ladder sheds the
  largest padding bucket and re-serves the same batch compiled; the
  artifact asserts zero dropped requests and >= 1 shed rung.
- **ladder off**: ``TRANSMOGRIFAI_RESOURCE_LADDER=0`` + the same sweep
  fault against a single-family selector must FAIL (every candidate
  failed) — proof the ladder is additive, not a silent behavior change.

Writes ``benchmarks/RESOURCE_RESILIENCE.json`` (schema:
``scripts/check_artifacts.py`` ``resource_resilience``) and prints one
JSON line. Run: ``python benchmarks/bench_resource_resilience.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time
import warnings

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("TRANSMOGRIFAI_SWEEP_STACKED", "1")
os.environ.setdefault("TRANSMOGRIFAI_TREE_STACKED", "1")

import numpy as np

ROWS = int(os.environ.get("RESILIENCE_ROWS", 4_000))
SERVE_REQUESTS = int(os.environ.get("RESILIENCE_REQUESTS", 400))
FOLDS = 3


def _frame(ft, frame_cls, n=ROWS, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n).astype(float)
    x = rng.normal(size=n) + 0.8 * y
    return frame_cls.from_dict({
        "x": (ft.Real, x.tolist()),
        "x2": (ft.Real, rng.normal(size=n).tolist()),
        "label": (ft.RealNN, y.tolist()),
    })


def _selector(single_family: bool = False):
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.models.trees import OpGBTClassifier
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, DataSplitter,
    )
    fams = [(OpLogisticRegression(max_iter=25),
             [{"reg_param": r} for r in (0.01, 0.1)])]
    if not single_family:
        fams.append((OpGBTClassifier(num_rounds=4, max_depth=2),
                     [{"learning_rate": lr} for lr in (0.1, 0.3)]))
    return BinaryClassificationModelSelector.with_cross_validation(
        n_folds=FOLDS, seed=1, models_and_parameters=fams,
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1))


def _train(selector, frame):
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow
    UID.reset()
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    vec = transmogrify(list(feats.values()), min_support=1)
    pred = label.transform_with(selector, vec)
    return (Workflow().set_input_frame(frame)
            .set_result_features(pred).train())


def _winner_parity(s1, s2) -> float:
    """Max abs metric delta between two selector summaries (validation
    results + train/holdout evaluation of the winner)."""
    if s1.best_model_name != s2.best_model_name:
        return float("inf")
    d = 0.0
    v1 = {r.model_name: r.metric_values for r in s1.validation_results}
    v2 = {r.model_name: r.metric_values for r in s2.validation_results}
    if set(v1) != set(v2):
        return float("inf")
    for k in v1:
        for m in v1[k]:
            d = max(d, abs(float(v1[k][m]) - float(v2[k][m])))

    def flat(doc, out):
        for k, v in doc.items():
            if isinstance(v, dict):
                flat(v, out)
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                out.append(float(v))
        return out

    for a, b in ((s1.train_evaluation, s2.train_evaluation),
                 (s1.holdout_evaluation, s2.holdout_evaluation)):
        fa, fb = flat(a, []), flat(b, [])
        if len(fa) != len(fb):
            return float("inf")
        d = max(d, max((abs(x - z) for x, z in zip(fa, fb)), default=0.0))
    return d


def main() -> int:
    from transmogrifai_tpu import dsl  # noqa: F401 — installs operators
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.faults import fault_plan
    from transmogrifai_tpu.utils.resources import resource_counters
    import jax

    platform = jax.devices()[0].platform
    warnings.simplefilter("ignore")
    frame = _frame(ft, fr.HostFrame)
    t0 = time.monotonic()

    # -- sweep leg: each rung faulted in its own run (the fault indexes
    # are per-site invocation counts, and a taken rung itself advances
    # them — two runs keep each injection aimed at its intended unit):
    # #0 = the LR stacked dispatch (rung: per-fold loop), #1 = the GBT
    # depth-group chunk (rung: halved lane chunks)
    s_clean = _train(_selector(), frame).selector_summary()
    resource_counters.reset()
    t_sweep = time.monotonic()
    with fault_plan("oom@sweep.fit#0"):
        s_oom_a = _train(_selector(), frame).selector_summary()
    with fault_plan("oom@sweep.fit#1"):
        s_oom_b = _train(_selector(), frame).selector_summary()
    sweep_wall = time.monotonic() - t_sweep
    s_oom = s_oom_a
    sweep_counters = resource_counters.to_json()
    sweep_parity = max(_winner_parity(s_oom_a, s_clean),
                       _winner_parity(s_oom_b, s_clean))

    # -- ladder-off leg: the same fault must fail fast ----------------------
    os.environ["TRANSMOGRIFAI_RESOURCE_LADDER"] = "0"
    fails_fast = False
    try:
        with fault_plan("oom@sweep.fit#0x*"):
            _train(_selector(single_family=True), frame)
    except RuntimeError as e:
        fails_fast = "every candidate failed" in str(e)
    finally:
        os.environ["TRANSMOGRIFAI_RESOURCE_LADDER"] = "1"

    # -- serving leg --------------------------------------------------------
    from transmogrifai_tpu.serving import ScoringServer
    model = _train(_selector(single_family=True), frame)
    rng = np.random.default_rng(7)
    rows = [{"x": float(v), "x2": float(w)}
            for v, w in zip(rng.normal(size=SERVE_REQUESTS),
                            rng.normal(size=SERVE_REQUESTS))]
    resource_counters.reset()
    server = ScoringServer(model, max_batch=64, min_bucket=8,
                           max_wait_ms=1.0)
    server.start(warmup_row=rows[0])
    buckets_before = len(server.scorer.buckets)
    t_serve = time.monotonic()
    with fault_plan("oom@serving.dispatch#2"):
        futs = [server.submit_blocking(dict(r)) for r in rows]
        results = [f.result(timeout=60) for f in futs]
    serve_wall = time.monotonic() - t_serve
    snap = server.snapshot(mirror_to_profiler=False)
    buckets_shed = buckets_before - len(server.scorer.buckets)
    server.stop()
    serve_counters = resource_counters.to_json()
    dropped = (snap["requests"]["admitted"]
               - snap["requests"]["completed"]
               - snap["requests"]["failed"])
    errors = sum(1 for r in results if not isinstance(r, dict))

    result = {
        "metric": "resource_resilience",
        "platform": platform,
        "rows": ROWS,
        "requests": SERVE_REQUESTS,
        "wall_s": round(time.monotonic() - t0, 3),
        "sweep": {
            "completed": True,
            "wall_s": round(sweep_wall, 3),
            "winner": s_oom.best_model_name,
            "winner_parity": sweep_parity,
            "degradations": sweep_counters["degradations"],
            "rungs": sweep_counters["degradationsBySite"],
            "oom_injected": sweep_counters["oomEvents"],
        },
        "serving": {
            "wall_s": round(serve_wall, 3),
            "requests": SERVE_REQUESTS,
            "zero_dropped": dropped == 0 and errors == 0
            and snap["requests"]["failed"] == 0,
            "failed": snap["requests"]["failed"],
            "degradations": serve_counters["degradations"],
            "buckets_shed": buckets_shed,
            "degraded_mode_entries": snap["degraded"]["entries"],
        },
        "ladder_disabled_fails_fast": fails_fast,
        "counters": {
            "degradations": (sweep_counters["degradations"]
                             + serve_counters["degradations"]),
            "oomEvents": (sweep_counters["oomEvents"]
                          + serve_counters["oomEvents"]),
        },
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "RESOURCE_RESILIENCE.json")
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    ok = (sweep_parity <= 1e-5 and result["serving"]["zero_dropped"]
          and fails_fast and result["counters"]["degradations"] >= 2)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
