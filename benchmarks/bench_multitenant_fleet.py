"""Multi-tenant fleet bench: 1000+ lazily registered models, Zipf
traffic, demand paging through the RAM budget, and the fairness
experiment.

Topology: ONE tiny binary AutoML model is trained and saved once; its
checkpoint is symlinked into ``N_MODELS`` versioned tenant dirs
(``root/m0042/v1``). Every tenant therefore shares the same TRUE
content fingerprint — so compiled programs are shared in the HBM-tier
``ProgramCache`` exactly as a real fleet of same-architecture org
models would share them — while each dir still pays its own stat
fingerprint, registry entry, RAM-tier record, and lane.

Four measured legs, all in-process threads (``submit_blocking``
absorbs every 503, so throttled is retried and NOTHING drops):

1. **registration** — ``register_dir`` over the 1000 dirs with
   ``np.load`` spy-wrapped: the artifact commits the wall AND the
   load count, which must be ZERO (stat-only lazy registration).
2. **paging sweep** — Zipf-ranked traffic across the whole fleet
   with a RAM budget ~``BUDGET_MODELS`` models deep: cold starts are
   measured (``TierMetrics`` reservoir), demotions forced, demoted
   tenants transparently re-paged.
3. **hot leg** — closed-loop threads over the ``HOT_MODELS`` hottest
   tenants (already resident): the interactive p50/p99 while the
   long tail stays cold around them.
4. **fairness** — a victim tenant's sequential p99 is measured with
   the fleet quiet, then re-measured while ``FLOOD_THREADS`` threads
   flood ONE hot tenant past its admission rate. The flood must be
   throttled (>= 1), the victim never dropped, and its p99 must stay
   within ``check_artifacts.MAX_MT_FAIRNESS_RATIO`` of baseline.

Acceptance bounds live in ``scripts/check_artifacts.py``
(``_validate_multitenant_fleet``), gated by
``tests/test_bench_artifacts.py`` against the committed
``benchmarks/MULTITENANT_FLEET.json``.

Run: ``python benchmarks/bench_multitenant_fleet.py``. Knobs:
MT_MODELS, MT_SWEEP_REQUESTS, MT_HOT_SECONDS, MT_CLIENTS,
MT_BUDGET_MODELS, MT_RATE_PER_S.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import tempfile
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

N_MODELS = int(os.environ.get("MT_MODELS", 1000))
SWEEP_REQUESTS = int(os.environ.get("MT_SWEEP_REQUESTS", 3000))
HOT_SECONDS = float(os.environ.get("MT_HOT_SECONDS", 5.0))
CLIENTS = int(os.environ.get("MT_CLIENTS", 4))
#: RAM budget in units of one model's stat footprint — deep enough to
#: hold the hot set, far too shallow for the sweep's distinct tenants
BUDGET_MODELS = int(os.environ.get("MT_BUDGET_MODELS", 40))
RATE_PER_S = float(os.environ.get("MT_RATE_PER_S", 100.0))
HOT_MODELS = 8
FLOOD_THREADS = 3
FLOOD_SECONDS = 4.0
VICTIM_SAMPLES = 40
ZIPF_S = 1.3
TRAIN_ROWS = 600
D_NUM = 6


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_multitenant_fleet.py",
                "transmogrifai_tpu/tenancy/store.py",
                "transmogrifai_tpu/tenancy/fairness.py",
                "transmogrifai_tpu/tenancy/popularity.py",
                "transmogrifai_tpu/serving/fleet.py",
                "transmogrifai_tpu/serving/registry.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train_canonical(root: str):
    """One tiny fitted binary workflow saved at ``root/canonical``;
    returns (checkpoint_path, request_rows)."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow

    UID.reset()
    rng = np.random.default_rng(3)
    n = TRAIN_ROWS
    X = rng.normal(size=(n, D_NUM))
    color = rng.choice(["red", "green", "blue"], size=n)
    logit = (1.3 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2]
             + 1.1 * (color == "red"))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
    cols = {"y": (ft.RealNN, y.tolist()),
            "color": (ft.PickList, color.tolist())}
    for j in range(D_NUM):
        cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
    frame = fr.HostFrame.from_dict(cols)
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify(
        [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=25), [{}])])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    path = os.path.join(root, "canonical")
    model.save(path)
    rows = []
    for i in range(256):
        row = {f"x{j}": float(X[i, j]) for j in range(D_NUM)}
        row["color"] = str(color[i])
        rows.append(row)
    return path, rows


def _fan_out(fleet_root: str, canonical: str, n: int) -> list:
    """Symlink the canonical checkpoint into ``n`` versioned tenant
    dirs. Symlinks, not copies: 1000 real checkpoints would measure
    the filesystem, not the registry."""
    ids = []
    names = os.listdir(canonical)
    for i in range(n):
        model_id = f"m{i:04d}"
        d = os.path.join(fleet_root, model_id, "v1")
        os.makedirs(d)
        for name in names:
            os.symlink(os.path.join(canonical, name),
                       os.path.join(d, name))
        ids.append(model_id)
    return ids


def _pctl(samples: list, p: float) -> float:
    s = sorted(samples)
    i = min(int(p * (len(s) - 1) + 0.5), len(s) - 1)
    return round(s[i], 3)


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import numpy as np

    import jax

    platform = jax.devices()[0].platform

    from transmogrifai_tpu.serving.fleet import FleetServer
    from transmogrifai_tpu.tenancy import TenancyConfig, model_file_bytes

    t_start = time.time()
    root = tempfile.mkdtemp(prefix="mt_fleet_")
    canonical, rows = _train_canonical(root)
    per_model_bytes = model_file_bytes(canonical)
    print(f"# trained canonical model in {time.time() - t_start:.1f}s "
          f"({per_model_bytes} bytes) on {platform}", file=sys.stderr)

    fleet_root = os.path.join(root, "tenants")
    os.makedirs(fleet_root)
    t0 = time.time()
    ids = _fan_out(fleet_root, canonical, N_MODELS)
    print(f"# fanned out {len(ids)} tenant dirs in "
          f"{time.time() - t0:.1f}s", file=sys.stderr)

    budget = per_model_bytes * BUDGET_MODELS
    fleet = FleetServer(
        tenancy=TenancyConfig(ram_budget_bytes=budget,
                              rate_per_s=RATE_PER_S),
        max_batch=16, max_wait_ms=1.0)

    # -- leg 1: lazy registration under an np.load spy ------------------
    loads = [0]
    orig_load = np.load

    def _spy(*args, **kwargs):
        loads[0] += 1
        return orig_load(*args, **kwargs)

    np.load = _spy
    try:
        t0 = time.time()
        entries = fleet.register_dir(fleet_root)
        register_wall = time.time() - t0
        loads_at_register = loads[0]
    finally:
        np.load = orig_load
    assert len(entries) == N_MODELS
    fleet.start()
    print(f"# registered {len(entries)} models COLD in "
          f"{register_wall:.2f}s ({loads_at_register} checkpoint "
          "loads)", file=sys.stderr)

    store = fleet.tenancy_store
    dropped = [0]

    def _score(model_id: str, row: dict, samples=None) -> None:
        t0 = time.perf_counter()
        try:
            fleet.submit_blocking(model_id, row).result(timeout=120)
        except Exception as e:  # noqa: BLE001 — a drop fails the bench
            dropped[0] += 1
            print(f"# DROP {model_id}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return
        if samples is not None:
            samples.append((time.perf_counter() - t0) * 1e3)

    # -- leg 2: Zipf paging sweep across the whole fleet ----------------
    rng = np.random.default_rng(7)
    ranks = np.minimum(rng.zipf(ZIPF_S, size=SWEEP_REQUESTS),
                       N_MODELS) - 1
    sweep_samples: list = []
    scored_models: set = set()
    lock = threading.Lock()
    cursor = [0]

    def _sweep_worker():
        while True:
            with lock:
                i = cursor[0]
                if i >= SWEEP_REQUESTS:
                    return
                cursor[0] = i + 1
            model_id = ids[int(ranks[i])]
            with lock:
                scored_models.add(model_id)
            _score(model_id, rows[i % len(rows)], sweep_samples)

    t0 = time.time()
    workers = [threading.Thread(target=_sweep_worker, daemon=True)
               for _ in range(CLIENTS)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    sweep_wall = time.time() - t0
    print(f"# sweep: {len(sweep_samples)} requests over "
          f"{len(scored_models)} distinct models in {sweep_wall:.1f}s "
          f"(resident={store.resident_count}, "
          f"demotions={store.metrics.demotions_ram})", file=sys.stderr)

    # -- leg 3: hot tenants (resident) at closed-loop speed -------------
    hot_ids = [ids[i] for i in range(HOT_MODELS)]
    for model_id in hot_ids:     # make sure every hot tenant is paged
        _score(model_id, rows[0])
    hot_samples: list = []
    hot_stop = time.time() + HOT_SECONDS

    def _hot_worker(idx: int):
        i = idx
        while time.time() < hot_stop:
            _score(hot_ids[i % len(hot_ids)], rows[i % len(rows)],
                   hot_samples)
            i += 1

    t0 = time.time()
    workers = [threading.Thread(target=_hot_worker, args=(i,),
                                daemon=True)
               for i in range(CLIENTS)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    hot_wall = time.time() - t0
    hot_rps = len(hot_samples) / max(hot_wall, 1e-9)
    print(f"# hot leg: {len(hot_samples)} requests, "
          f"{hot_rps:.0f} rps, p99 {_pctl(hot_samples, 0.99)}ms",
          file=sys.stderr)

    # -- leg 4: fairness — victim p99 with and without a flood ----------
    victim = ids[N_MODELS // 2]
    flood_target = hot_ids[0]
    _score(victim, rows[0])      # page the victim in
    baseline: list = []
    for i in range(VICTIM_SAMPLES):
        _score(victim, rows[i % len(rows)], baseline)

    flood_stop = [time.time() + FLOOD_SECONDS]

    def _flood_worker():
        i = 0
        while time.time() < flood_stop[0]:
            _score(flood_target, rows[i % len(rows)])
            i += 1

    flooders = [threading.Thread(target=_flood_worker, daemon=True)
                for _ in range(FLOOD_THREADS)]
    for f in flooders:
        f.start()
    time.sleep(0.5)              # let the flood saturate its bucket
    flooded: list = []
    for i in range(VICTIM_SAMPLES):
        _score(victim, rows[i % len(rows)], flooded)
    flood_stop[0] = 0.0
    for f in flooders:
        f.join()

    fair_rows = fleet.admission.metrics.tenant_rows()
    hot_throttled = fair_rows.get(flood_target, {}).get("throttled", 0)
    baseline_p99 = _pctl(baseline, 0.99)
    flood_p99 = _pctl(flooded, 0.99)
    ratio = round(flood_p99 / max(baseline_p99, 1e-9), 3)
    print(f"# fairness: victim p99 {baseline_p99}ms -> {flood_p99}ms "
          f"under flood (ratio {ratio}), hot tenant throttled "
          f"{hot_throttled}x", file=sys.stderr)

    # -- assemble -------------------------------------------------------
    tiers = store.metrics
    cold_ms = tiers.cold_start_percentiles_ms()
    cache_doc = fleet.program_cache.to_json()
    tenancy_doc = store.to_json()
    fleet.stop()

    requests = (len(sweep_samples) + len(hot_samples) + len(baseline)
                + len(flooded))
    wall_s = time.time() - t_start
    zero_dropped = dropped[0] == 0

    from scripts.check_artifacts import _validate_multitenant_fleet

    artifact = {
        "metric": "multitenant_fleet",
        "platform": platform,
        "requests": int(requests),
        "wall_s": round(wall_s, 3),
        "models": int(N_MODELS),
        "zero_dropped": zero_dropped,
        "distinct_models_scored": int(len(scored_models)),
        "registration": {
            "models": int(N_MODELS),
            "wall_s": round(register_wall, 3),
            "loads_at_register": int(loads_at_register),
        },
        "hot": {
            "rps": round(hot_rps, 1),
            "p50_ms": _pctl(hot_samples, 0.50),
            "p99_ms": _pctl(hot_samples, 0.99),
        },
        "cold_start_ms": cold_ms,
        "fairness": {
            "baseline_p99_ms": baseline_p99,
            "flood_p99_ms": flood_p99,
            "ratio": ratio,
            "hot_throttled": int(hot_throttled),
            "cold_dropped": 0 if zero_dropped else int(dropped[0]),
        },
        "tiers": {
            "promotions_disk_ram": int(tiers.promotions_disk_ram),
            "promotions_ram_hbm": int(tiers.promotions_ram_hbm),
            "demotions_ram": int(tiers.demotions_ram),
            "demotions_hbm": int(tiers.demotions_hbm),
            "ram_budget_bytes": int(budget),
        },
        "sweep": {
            "requests": int(len(sweep_samples)),
            "wall_s": round(sweep_wall, 3),
            "zipf_s": ZIPF_S,
            "p50_ms": _pctl(sweep_samples, 0.50),
            "p99_ms": _pctl(sweep_samples, 0.99),
        },
        "clients": CLIENTS,
        "rate_per_s": RATE_PER_S,
        "model_file_bytes": int(per_model_bytes),
        "tenancy": tenancy_doc,
        "cache": cache_doc,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    errors = _validate_multitenant_fleet(artifact)
    artifact["ok"] = not errors
    artifact["notes"] = errors

    out_path = os.path.join(HERE, "MULTITENANT_FLEET.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
