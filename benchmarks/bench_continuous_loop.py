"""Closed-loop continuous AutoML demo: one long-running loop that keeps
serving while its model is retrained and hot-swapped under it.

Topology: the MAIN process trains an initial binary model on
in-distribution data and runs a ``continuous.ContinuousLoop`` (stream
ingest + drift windows + retrain orchestration + fleet serving with the
HTTP endpoint). Two concurrent threads drive the scenario:

- a **producer** writes stream micro-batch CSVs — first in-distribution,
  then with a covariate shift (x1 location moved by 4 sigma) injected
  mid-stream;
- a **live-traffic client** POSTs ``/score/live`` requests in a closed
  loop over a persistent connection for the whole run, straight through
  the drift trigger, the retrain, and the shadow-gated hot-swap.

Measured and committed to ``benchmarks/CONTINUOUS_LOOP.json``:

- **drift_detected** + the triggering window's measured divergence
  (``drift_score``, JS),
- **retrain_wall_s** (the ``continuous.retrain`` span) and
  **swap_wall_s** (``hot_swap``'s own wall: candidate warm + shadow gate
  + alias flip + old-lane drain),
- **staleness_s**: drift-trigger to promotion, vs the configured
  **staleness_bound_s** (acceptance: within bound),
- **zero_dropped**: every live request got a 200 (503 backpressure is
  retried, not dropped) and the fleet settled everything it admitted,
- **zero_lost_rows**: rows consumed == rows produced, zero skipped
  batches (counter-asserted from both sides of the stream),
- the loop lifecycle counters (triggers/retrains/promotions/rollbacks)
  and the promoted version.

Platform honesty: the artifact records the measured backend verbatim;
``CONTINUOUS_EXPECT_ACCEL=1`` makes a CPU fallback a hard error instead
of a mislabeled "accelerator" result.

Run: ``python benchmarks/bench_continuous_loop.py``. Knobs:
CONTINUOUS_TRAIN_ROWS, CONTINUOUS_BATCH_ROWS, CONTINUOUS_PRE_BATCHES,
CONTINUOUS_SHIFT_BATCHES.
"""

from __future__ import annotations

import datetime
import hashlib
import http.client
import json
import os
import sys
import tempfile
import threading
import time
import warnings

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

TRAIN_ROWS = int(os.environ.get("CONTINUOUS_TRAIN_ROWS", 400))
BATCH_ROWS = int(os.environ.get("CONTINUOUS_BATCH_ROWS", 50))
PRE_BATCHES = int(os.environ.get("CONTINUOUS_PRE_BATCHES", 4))
SHIFT_BATCHES = int(os.environ.get("CONTINUOUS_SHIFT_BATCHES", 8))
WINDOW_BATCHES = 2
SHIFT = 4.0
STALENESS_BOUND_S = float(os.environ.get("CONTINUOUS_STALENESS_BOUND_S",
                                         600.0))


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_continuous_loop.py",
                "transmogrifai_tpu/continuous/loop.py",
                "transmogrifai_tpu/continuous/drift.py",
                "transmogrifai_tpu/continuous/state.py",
                "transmogrifai_tpu/serving/fleet.py",
                "transmogrifai_tpu/readers/streaming.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _build_workflow(rng):
    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow
    import numpy as np

    UID.reset()
    x1 = rng.normal(size=TRAIN_ROWS)
    x2 = rng.normal(size=TRAIN_ROWS)
    logit = 1.5 * x1 - x2
    y = (rng.uniform(size=TRAIN_ROWS)
         < 1 / (1 + np.exp(-logit))).astype(float)
    host = fr.HostFrame.from_dict({
        "label": (ft.RealNN, y.tolist()),
        "x1": (ft.Real, x1.tolist()),
        "x2": (ft.Real, x2.tolist()),
    })
    feats = FeatureBuilder.from_frame(host, response="label")
    vec = transmogrify([feats["x1"], feats["x2"]])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[
            (OpLogisticRegression(max_iter=25), [{}])])
    pred = feats["label"].transform_with(sel, vec)
    wf = Workflow().set_input_frame(host).set_result_features(pred, vec)
    return wf, host


def _producer(stream_dir: str, rng, produced: dict,
              started: threading.Event) -> None:
    """Write the micro-batch stream: PRE_BATCHES in-distribution, then
    the covariate shift. Atomic rename-into-place per file."""
    import numpy as np
    started.wait()
    for i in range(PRE_BATCHES + SHIFT_BATCHES):
        shift = SHIFT if i >= PRE_BATCHES else 0.0
        lines = ["label,x1,x2"]
        for _ in range(BATCH_ROWS):
            x1 = rng.normal(loc=shift)
            x2 = rng.normal()
            p = 1 / (1 + np.exp(-(1.5 * x1 - x2)))
            lines.append(f"{float(rng.uniform() < p)},{x1},{x2}")
        path = os.path.join(stream_dir, f"b{i:03d}.csv")
        with open(path + ".tmp", "w") as fh:
            fh.write("\n".join(lines) + "\n")
        os.replace(path + ".tmp", path)
        produced["rows"] += BATCH_ROWS
        produced["batches"] += 1
        time.sleep(0.05)


def _traffic(port: int, rows: list, stop: threading.Event,
             out: dict) -> None:
    """Closed-loop live scoring over one persistent connection; 503
    backpressure is retried (never dropped), anything else non-200 is a
    drop. Latencies recorded in ms."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    i = 0
    while not stop.is_set():
        row = rows[i % len(rows)]
        i += 1
        body = json.dumps(row)
        t0 = time.monotonic()
        try:
            conn.request("POST", "/score/live", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status == 503:
                out["retried_503"] += 1
                time.sleep(float(resp.getheader("Retry-After", 0.05)))
                continue
            if resp.status != 200:
                out["errors"] += 1
                continue
            json.loads(payload)
            out["ok"] += 1
            out["latencies_ms"].append((time.monotonic() - t0) * 1e3)
        except Exception:  # noqa: BLE001 — conn reset counts as a drop
            out["errors"] += 1
            conn.close()
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
    conn.close()


def main() -> int:
    import numpy as np

    import jax

    from transmogrifai_tpu.continuous import (
        ContinuousLoop, DriftConfig, LoopState,
    )
    from transmogrifai_tpu.utils.tracing import recorder

    platform = jax.default_backend()
    if os.environ.get("CONTINUOUS_EXPECT_ACCEL") == "1" \
            and platform == "cpu":
        print("CONTINUOUS_EXPECT_ACCEL=1 but jax backend is cpu",
              file=sys.stderr)
        return 1

    rng = np.random.default_rng(0)
    print(f"# training initial model on {TRAIN_ROWS} rows "
          f"({platform})", file=sys.stderr)
    wf, host = _build_workflow(rng)
    t0 = time.monotonic()
    model = wf.train()
    print(f"# initial train: {time.monotonic() - t0:.1f}s",
          file=sys.stderr)

    tmp = tempfile.mkdtemp(prefix="bench_continuous_")
    stream_dir = os.path.join(tmp, "stream")
    state_dir = os.path.join(tmp, "state")
    os.makedirs(stream_dir)

    produced = {"rows": 0, "batches": 0}
    started = threading.Event()
    stop_traffic = threading.Event()
    traffic_out = {"ok": 0, "errors": 0, "retried_503": 0,
                   "latencies_ms": []}
    live_rows = [{"x1": float(rng.normal()), "x2": float(rng.normal())}
                 for _ in range(64)]

    producer = threading.Thread(
        target=_producer, args=(stream_dir, rng, produced, started),
        daemon=True)
    traffic_thread = None

    def on_started(lp: ContinuousLoop) -> None:
        nonlocal traffic_thread
        traffic_thread = threading.Thread(
            target=_traffic,
            args=(lp.metrics_http.port, live_rows, stop_traffic,
                  traffic_out),
            daemon=True)
        traffic_thread.start()
        started.set()  # stream begins only once live traffic flows

    def on_stopping(_lp: ContinuousLoop) -> None:
        # quiesce the client BEFORE the endpoint tears down: an error
        # from a deliberately-stopped server is not a dropped request
        stop_traffic.set()
        if traffic_thread is not None:
            traffic_thread.join(timeout=30)

    recorder.reset()
    loop = ContinuousLoop(
        wf, stream_dir, state_dir, model_id="live",
        pattern="*.csv", initial_model=model, reference_frame=host,
        drift=DriftConfig(js_threshold=0.3, consecutive_windows=2,
                          cooldown_windows=2),
        window_batches=WINDOW_BATCHES,
        max_buffer_batches=2 * WINDOW_BATCHES,
        poll_interval_s=0.05, timeout_s=5.0,
        staleness_bound_s=STALENESS_BOUND_S,
        metrics_port=0, on_started=on_started, on_stopping=on_stopping)
    producer.start()
    t_loop = time.monotonic()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        report = loop.run()
    loop_wall = time.monotonic() - t_loop
    stop_traffic.set()
    if traffic_thread is not None:
        traffic_thread.join(timeout=30)
    producer.join(timeout=30)

    spans = recorder.spans
    retrain_walls = [s.wall_s for s in spans
                     if s.name == "continuous.retrain"]
    counters = report["counters"]
    promotion = report["promotions"][-1] if report["promotions"] else {}
    swap = promotion.get("swap", {})
    lat = sorted(traffic_out["latencies_ms"])

    def pct(p: float) -> float:
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3) \
            if lat else 0.0

    # the triggering decision's driving drift score
    decisions = LoopState(state_dir, "live").decisions
    trigger_scores = [
        max(v.get("js", 0.0) for v in d.get("scores", {}).values())
        for d in decisions if d.get("triggered")]

    serving = report.get("serving", {})
    zero_dropped = (traffic_out["errors"] == 0
                    and traffic_out["ok"] > 0
                    and serving.get("failed") == 0
                    and serving.get("admitted") == serving.get(
                        "completed"))
    zero_lost = (counters["rows"] == produced["rows"]
                 and counters["batches"] == produced["batches"]
                 and counters["skippedBatches"] == 0
                 and not report["streamSkippedFiles"])

    art = {
        "metric": "continuous_loop",
        "platform": platform,
        "rows": produced["rows"],
        "requests": traffic_out["ok"],
        "loop_wall_s": round(loop_wall, 3),
        "windows": report["windows"],
        "drift_detected": counters["driftTriggers"] >= 1,
        "drift_score": round(max(trigger_scores), 6) if trigger_scores
        else 0.0,
        "retrain_wall_s": round(max(retrain_walls), 3)
        if retrain_walls else 0.0,
        "swap_wall_s": swap.get("wallSeconds", 0.0),
        "staleness_s": promotion.get("stalenessSeconds", 0.0),
        "staleness_bound_s": STALENESS_BOUND_S,
        "zero_dropped": zero_dropped,
        "zero_lost_rows": zero_lost,
        "promoted": {"version": report["activeVersion"] or "",
                     "fromVersion": swap.get("fromVersion"),
                     "shadowRows": swap.get("shadowRows")},
        "counters": {k: counters[k] for k in
                     ("driftTriggers", "retrains", "promotions",
                      "rollbacks")},
        "serving": {"requests_ok": traffic_out["ok"],
                    "errors": traffic_out["errors"],
                    "retried_503": traffic_out["retried_503"],
                    "p50_ms": pct(0.50), "p99_ms": pct(0.99)},
        "stream": dict(produced),
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    print(json.dumps(art, indent=2))
    return _validate_and_save(art)


def _validate_and_save(art: dict) -> int:
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_artifacts", os.path.join(REPO, "scripts",
                                        "check_artifacts.py"))
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    errors = checker.validate_artifact(art)
    if errors:
        for e in errors:
            print(f"ARTIFACT INVALID: {e}", file=sys.stderr)
        return 1
    out = os.path.join(HERE, "CONTINUOUS_LOOP.json")
    tmp_path = out + ".tmp"
    with open(tmp_path, "w") as fh:
        json.dump(art, fh, indent=2)
        fh.write("\n")
    os.replace(tmp_path, out)
    print(f"# wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
