"""Multi-model serving-fleet load test: sustained multi-process traffic
across >= 3 registered models with one mid-run zero-downtime hot-swap.

Topology: the MAIN process trains three small binary AutoML models (one
endpoint each: ``model_a``/``model_b``/``model_c``) plus a retrained
``model_b`` v2, saves them in the registry's versioned layout, and runs a
``serving.FleetServer`` (per-model admission lanes over the shared
compiled-program cache) with its HTTP endpoint (``POST /score/<id>``).
``FLEET_CLIENTS`` separate OS processes (spawned, no jax — real wire
clients) drive closed-loop round-robin traffic over persistent
connections for ``FLEET_DURATION_S``; mid-run the main process promotes
``model_b`` v2 through the full hot-swap path (candidate warmup, shadow
parity gate on live rows, atomic alias flip, old-lane drain).

Measured and committed to ``benchmarks/SERVING_FLEET.json``:

- **aggregate_rps** + per-model request counts and p50/p99 latency,
- **p99_under_swap_ms** (requests completed while ``hot_swap`` was in
  flight) vs **steady_p99_ms** (everything outside the swap window) —
  acceptance: under-swap p99 <= 2x steady (``check_artifacts.py``),
- **zero_dropped**: every request a client sent got a response and none
  errored (503 backpressure is retried client-side, not dropped),
- **compile-storm bound**: post-warmup compiles per (model, bucket) — 0
  means steady-state fleet traffic never recompiled, including the
  swapped-in version (warmed before taking traffic),
- shared-cache accounting (insertions/evictions/hits/bytes).

Platform honesty: the artifact records the measured backend verbatim;
``SERVING_FLEET_EXPECT_ACCEL=1`` makes a CPU fallback a hard error
instead of a mislabeled "accelerator" result.

Run: ``python benchmarks/bench_serving_fleet.py``. Knobs: FLEET_CLIENTS,
FLEET_DURATION_S, FLEET_MAX_BATCH, FLEET_TRAIN_ROWS, FLEET_SWAP_AT.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import multiprocessing
import os
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

CLIENTS = int(os.environ.get("FLEET_CLIENTS", 2))
DURATION_S = float(os.environ.get("FLEET_DURATION_S", 12.0))
MAX_BATCH = int(os.environ.get("FLEET_MAX_BATCH", 32))
TRAIN_ROWS = int(os.environ.get("FLEET_TRAIN_ROWS", 1200))
#: fraction of the run after which the hot-swap fires
SWAP_AT = float(os.environ.get("FLEET_SWAP_AT", 0.4))
MODELS = ("model_a", "model_b", "model_c")
D_NUM = 8


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_serving_fleet.py",
                "transmogrifai_tpu/serving/fleet.py",
                "transmogrifai_tpu/serving/registry.py",
                "transmogrifai_tpu/serving/compiled.py",
                "transmogrifai_tpu/serving/server.py",
                "transmogrifai_tpu/serving/http.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _client(idx: int, port: int, rows_by_model: dict, end_at: float,
            out_q) -> None:
    """One load-generator PROCESS: closed-loop round-robin requests over
    a persistent connection. Records (done_epoch_s, latency_ms, model)
    per completed request; 503 backpressure waits out the Retry-After
    hint and retries (shed, not dropped)."""
    import http.client
    import json as _json
    models = sorted(rows_by_model)
    samples = []  # (t_done, latency_ms, model)
    sent = got = errors = backpressure = 0
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    i = idx  # de-phase clients
    while time.time() < end_at:
        model = models[i % len(models)]
        rows = rows_by_model[model]
        body = _json.dumps(rows[i % len(rows)])
        t0 = time.perf_counter()
        try:
            conn.request("POST", f"/score/{model}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
        except Exception:  # noqa: BLE001 — reconnect and retry the slot
            conn.close()
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            continue
        sent += 1
        if resp.status == 503:
            backpressure += 1
            time.sleep(min(float(resp.headers.get("Retry-After", 0.01)),
                           0.25))
            continue
        latency_ms = (time.perf_counter() - t0) * 1e3
        if resp.status == 200 and payload:
            got += 1
            samples.append((time.time(), round(latency_ms, 3), model))
        else:
            errors += 1
        i += 1
    conn.close()
    out_q.put({"idx": idx, "sent": sent, "got": got, "errors": errors,
               "backpressure": backpressure, "samples": samples})


def _train_zoo(root: str) -> dict:
    """Three endpoints + a retrained model_b v2, saved in the registry
    layout. Returns request rows per model id."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow

    def train(seed: int, max_iter: int = 25):
        # UID.reset pins stage uids: versions of one endpoint must share
        # result-feature names (retrain-in-a-fresh-process analog)
        UID.reset()
        rng = np.random.default_rng(seed)
        n = TRAIN_ROWS
        X = rng.normal(size=(n, D_NUM))
        color = rng.choice(["red", "green", "blue"], size=n)
        logit = (1.3 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2]
                 + 1.1 * (color == "red"))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
        cols = {"y": (ft.RealNN, y.tolist()),
                "color": (ft.PickList, color.tolist())}
        for j in range(D_NUM):
            cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
        frame = fr.HostFrame.from_dict(cols)
        feats = FeatureBuilder.from_frame(frame, response="y")
        features = transmogrify(
            [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
        sel = BinaryClassificationModelSelector \
            .with_train_validation_split(
                seed=1, models_and_parameters=[
                    (OpLogisticRegression(max_iter=max_iter), [{}])])
        pred = feats["y"].transform_with(sel, features)
        model = (Workflow().set_input_frame(frame)
                 .set_result_features(pred, features).train())
        rows = []
        for i in range(512):
            k = i % n
            row = {f"x{j}": float(X[k, j]) for j in range(D_NUM)}
            row["color"] = str(color[k])
            rows.append(row)
        return model, rows

    rows_by_model = {}
    for mid, seed in zip(MODELS, (3, 7, 13)):
        model, rows = train(seed)
        if mid == "model_b":
            model.save(os.path.join(root, mid, "v1"))
            # the candidate: same data, one more optimizer iteration —
            # a rebuild-and-promote whose scores move only slightly, so
            # the shadow gate can hold a tight-ish tolerance honestly
            v2, _ = train(seed, max_iter=26)
            v2.save(os.path.join(root, mid, "v2"))
        else:
            model.save(os.path.join(root, mid))
        rows_by_model[mid] = rows
    return rows_by_model


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import tempfile

    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    if os.environ.get("SERVING_FLEET_EXPECT_ACCEL") == "1" \
            and platform == "cpu":
        print(json.dumps({"metric": "serving_fleet",
                          "error": "SERVING_FLEET_EXPECT_ACCEL=1 but the "
                                   "backend initialized as cpu; refusing "
                                   "to record a CPU wall as an "
                                   "accelerator result"}))
        return 1

    from transmogrifai_tpu.serving import FleetServer

    t0 = time.time()
    root = tempfile.mkdtemp(prefix="fleet_zoo_")
    rows_by_model = _train_zoo(root)
    print(f"# trained {len(MODELS)} models (+1 candidate) in "
          f"{time.time() - t0:.1f}s on {platform}", file=sys.stderr)

    # one padding bucket per model (min_bucket == max_batch): every
    # batch pads to MAX_BATCH, so a lane warms with ONE compile per
    # fused layer — which keeps the hot-swap's candidate-warmup CPU
    # burst (the only serving-visible cost of a swap) minimal
    fleet = FleetServer(max_batch=MAX_BATCH, max_wait_ms=2.0,
                        queue_capacity=4 * MAX_BATCH,
                        min_bucket=MAX_BATCH,
                        shadow_rows=16, metrics_port=0)
    fleet.register_dir(root)
    fleet.start(warmup_rows={m: rows_by_model[m][0] for m in MODELS})
    # operator prep: compile the candidate's programs into the shared
    # cache BEFORE traffic, so the mid-run hot_swap's lane warmup is
    # pure cache hits instead of a jit-trace burst racing live requests
    fleet.prewarm("model_b", "v2", rows_by_model["model_b"][0])
    port = fleet.metrics_http.port
    print(f"# fleet serving {MODELS} on 127.0.0.1:{port}",
          file=sys.stderr)

    # -- multi-process load + mid-run swap ------------------------------
    ctx = multiprocessing.get_context("spawn")  # no forked jax threads
    out_q = ctx.Queue()
    end_at = time.time() + DURATION_S
    procs = [ctx.Process(target=_client,
                         args=(i, port, rows_by_model, end_at, out_q),
                         daemon=True)
             for i in range(CLIENTS)]
    for p in procs:
        p.start()

    swap_report: dict = {}
    swap_window: list = [None, None]

    def do_swap():
        time.sleep(max(SWAP_AT * DURATION_S
                       - (time.time() - (end_at - DURATION_S)), 0.1))
        swap_window[0] = time.time()
        try:
            swap_report.update(fleet.hot_swap(
                "model_b", version="v2", tolerance=0.5))
            swap_report["promoted"] = True
        except Exception as e:  # noqa: BLE001 — recorded in the artifact
            swap_report["promoted"] = False
            swap_report["error"] = f"{type(e).__name__}: {e}"
        swap_window[1] = time.time()

    swapper = threading.Thread(target=do_swap)
    swapper.start()
    results = [out_q.get(timeout=DURATION_S + 120) for _ in procs]
    for p in procs:
        p.join(timeout=30)
    swapper.join(timeout=60)

    # -- compile-storm bound BEFORE stop (lanes still live) -------------
    compile_storm = {
        mid: {str(b): n for b, n in lane.post_warmup_compiles().items()}
        for mid, lane in fleet.active_lanes().items()}
    storm_max = max((n for per in compile_storm.values()
                     for n in per.values()), default=0)
    lane_reqs = {mid: lane.metrics.snapshot(mirror_to_profiler=False)
                 ["requests"]
                 for mid, lane in fleet.active_lanes().items()}
    cache_doc = fleet.program_cache.to_json()
    fleet_doc = fleet.metrics.to_json()
    versions = {mid: fleet.registry.active_version(mid) for mid in MODELS}
    fleet.stop()

    # -- aggregate ------------------------------------------------------
    sent = sum(r["sent"] for r in results)
    got = sum(r["got"] for r in results)
    errors = sum(r["errors"] for r in results)
    backpressure = sum(r["backpressure"] for r in results)
    samples = [s for r in results for s in r["samples"]]
    if not samples or swap_window[0] is None:
        print(json.dumps({"metric": "serving_fleet",
                          "error": "no samples or swap never ran"}))
        return 1
    t_done = np.array([s[0] for s in samples])
    lat = np.array([s[1] for s in samples])
    model_of = np.array([s[2] for s in samples])
    sw0, sw1 = swap_window
    in_swap = (t_done >= sw0) & (t_done <= sw1)
    if in_swap.sum() < 20:
        # a fast swap completes between few samples: widen the window so
        # the under-swap percentile rests on a real sample count (any
        # swap-induced stall still lands inside the widened window)
        in_swap = (t_done >= sw0 - 0.5) & (t_done <= sw1 + 0.5)
    # steady state excludes a guard band around the swap
    steady = (t_done < sw0 - 0.5) | (t_done > sw1 + 0.5)
    wall = float(t_done.max() - t_done.min())
    steady_p99 = float(np.percentile(lat[steady], 99)) if steady.any() \
        else None
    swap_p99 = float(np.percentile(lat[in_swap], 99)) if in_swap.any() \
        else None
    per_model = {}
    for mid in MODELS:
        sel = model_of == mid
        per_model[mid] = {
            "requests": int(sel.sum()),
            "p50_ms": round(float(np.percentile(lat[sel], 50)), 3),
            "p99_ms": round(float(np.percentile(lat[sel], 99)), 3),
            "admitted": lane_reqs.get(mid, {}).get("admitted"),
            "completed": lane_reqs.get(mid, {}).get("completed"),
            "version": versions.get(mid),
        }

    zero_dropped = bool(got == sent - backpressure and errors == 0
                        and swap_report.get("promoted"))
    ok = True
    notes = []
    if not zero_dropped:
        ok = False
        notes.append(f"drops/errors: sent={sent} got={got} "
                     f"errors={errors} backpressure={backpressure} "
                     f"swap={swap_report}")
    if storm_max > 0:
        ok = False
        notes.append(f"compile storm: post-warmup compiles {compile_storm}")
    if steady_p99 and swap_p99 and swap_p99 > 2.0 * steady_p99:
        ok = False
        notes.append(f"p99 under swap {swap_p99:.1f}ms > 2x steady "
                     f"{steady_p99:.1f}ms")

    artifact = {
        "metric": "serving_fleet",
        "unit": "rps",
        "platform": platform,
        "models": len(MODELS),
        "clients": CLIENTS,
        "requests": int(got),
        "duration_s": round(wall, 3),
        "max_batch": MAX_BATCH,
        "train_rows": TRAIN_ROWS,
        "aggregate_rps": round(got / max(wall, 1e-9), 1),
        "per_model": per_model,
        "steady_p99_ms": round(steady_p99, 3),
        "p99_under_swap_ms": round(swap_p99, 3) if swap_p99 else None,
        "swap_window_requests": int(in_swap.sum()),
        "zero_dropped": zero_dropped,
        "errors": int(errors),
        "backpressure_retries": int(backpressure),
        "swap": {
            "promoted": bool(swap_report.get("promoted")),
            "wall_s": swap_report.get("wallSeconds",
                                      round(sw1 - sw0, 6)),
            "from_version": swap_report.get("fromVersion"),
            "to_version": swap_report.get("toVersion"),
            "shadow_rows": swap_report.get("shadowRows", 0),
            "shadow_max_abs_diff": swap_report.get("shadowMaxAbsDiff"),
            "shadow_tolerance": 0.5,
        },
        "compile_storm": {
            "max_post_warmup_per_bucket": int(storm_max),
            "per_model": compile_storm,
        },
        "cache": cache_doc,
        "fleet": fleet_doc,
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "SERVING_FLEET.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
