"""Wire-speed data plane: the binary columnar frame wire vs the
per-row JSON wire against the SAME live replica, plus a through-router
passthrough leg and a mid-run hot-swap under framed load.

Topology: the main process trains one small binary AutoML endpoint
(``wire`` v1) plus a retrained candidate (v2), saves both in the
registry's versioned layout, and serves them through one
``serving.FleetServer`` on the event-loop HTTP front (binary wire
negotiated, the default). One closed-loop client thread per leg over a
persistent keep-alive connection — identical client discipline for
both wires, so the comparison is apples to apples. The router leg
stands up a real ``scaleout.Router`` in front of the same replica and
repeats both wires through the proxy hop (frames forwarded as opaque
bytes off the fixed-offset model-id peek).

Measured and committed to ``benchmarks/WIRE_SPEED.json``:

- **json leg**: one row per POST (the pre-wire fleet client shape) —
  rps here is rows/s == requests/s, with request p50/p99,
- **binary leg**: ``WIRE_ROWS_PER_FRAME`` rows per POST through the
  frame codec — rps is ROWS/s (the number that has to beat 10x the
  committed 436 rps baseline), request p50/p99 per frame, and the
  **encode/decode wall split per frame** (client-side codec cost,
  measured inside the timed loop — the honest rps includes it),
- **router**: both wires through the proxy hop (rows/s),
- **parity_vs_json**: max |binary - json| over every score field of
  ``PARITY_ROWS`` rows served both ways (acceptance <= 1e-5),
- **compile_storm**: post-warmup compiles per (lane, bucket) — framed
  columnar batches must ride the SAME padding-bucket programs the row
  lane warmed, so the bound is 0,
- **swap**: a mid-run ``hot_swap`` to v2 under framed load — zero
  client-visible drops, post-swap framed replies carry v2 lineage.

Platform honesty: the artifact records the measured backend verbatim;
``WIRE_EXPECT_ACCEL=1`` makes a CPU fallback a hard error instead of a
mislabeled "accelerator" result.

Run: ``python benchmarks/bench_wire_speed.py``. Knobs: WIRE_TRIALS,
WIRE_REQUESTS (json leg), WIRE_FRAMES (binary leg), WIRE_ROWS_PER_FRAME,
WIRE_TRAIN_ROWS, WIRE_MAX_BATCH, WIRE_SWAP_S.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

TRIALS = int(os.environ.get("WIRE_TRIALS", 2))
JSON_REQUESTS = int(os.environ.get("WIRE_REQUESTS", 400))
FRAMES = int(os.environ.get("WIRE_FRAMES", 300))
ROWS_PER_FRAME = int(os.environ.get("WIRE_ROWS_PER_FRAME", 64))
TRAIN_ROWS = int(os.environ.get("WIRE_TRAIN_ROWS", 900))
MAX_BATCH = int(os.environ.get("WIRE_MAX_BATCH", 64))
SWAP_S = float(os.environ.get("WIRE_SWAP_S", 6.0))
PARITY_ROWS = 64
D_NUM = 6
MODEL_ID = "wire"


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_wire_speed.py",
                "transmogrifai_tpu/serving/wireformat.py",
                "transmogrifai_tpu/serving/aiohttp_core.py",
                "transmogrifai_tpu/serving/http.py",
                "transmogrifai_tpu/serving/compiled.py",
                "transmogrifai_tpu/serving/fleet.py",
                "transmogrifai_tpu/scaleout/router.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _baseline_rps() -> float:
    """The committed pre-wire fleet HTTP rate being beaten (the
    ThreadingHTTPServer + per-row JSON seam number)."""
    try:
        doc = json.load(open(os.path.join(HERE, "SERVING_FLEET.json")))
        base = float(doc["aggregate_rps"])
        if base > 0:
            return base
    except (OSError, KeyError, TypeError, ValueError):
        pass
    return 436.2


def _train(root: str):
    """One endpoint (v1) + a retrained candidate (v2) in the versioned
    registry layout. Returns request rows."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow

    def train(max_iter: int):
        UID.reset()  # versions of one endpoint share feature names
        rng = np.random.default_rng(13)
        n = TRAIN_ROWS
        X = rng.normal(size=(n, D_NUM))
        color = rng.choice(["red", "green", "blue"], size=n)
        logit = (1.4 * X[:, 0] - 0.9 * X[:, 1] + 0.4 * X[:, 2]
                 + 1.2 * (color == "red"))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
        cols = {"y": (ft.RealNN, y.tolist()),
                "color": (ft.PickList, color.tolist())}
        for j in range(D_NUM):
            cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
        frame = fr.HostFrame.from_dict(cols)
        feats = FeatureBuilder.from_frame(frame, response="y")
        features = transmogrify(
            [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
        sel = BinaryClassificationModelSelector \
            .with_train_validation_split(
                seed=1, models_and_parameters=[
                    (OpLogisticRegression(max_iter=max_iter), [{}])])
        pred = feats["y"].transform_with(sel, features)
        model = (Workflow().set_input_frame(frame)
                 .set_result_features(pred, features).train())
        rows = []
        for i in range(max(256, ROWS_PER_FRAME)):
            k = i % n
            row = {f"x{j}": float(X[k, j]) for j in range(D_NUM)}
            row["color"] = str(color[k])
            rows.append(row)
        return model, rows

    v1, rows = train(25)
    v1.save(os.path.join(root, MODEL_ID, "v1"))
    v2, _ = train(26)
    v2.save(os.path.join(root, MODEL_ID, "v2"))
    return rows


def _diff(a: dict, b: dict) -> float:
    """Max abs difference over every numeric score field (dicts one
    level deep, lists elementwise)."""
    d = 0.0
    for k, av in a.items():
        bv = b[k]
        if av is None or bv is None:
            if not (av is None and bv is None):
                raise AssertionError(f"null mismatch on {k!r}")
        elif isinstance(av, dict):
            for kk in av:
                d = max(d, abs(float(av[kk]) - float(bv[kk])))
        elif isinstance(av, (list, tuple)):
            d = max(d, max((abs(x - z) for x, z in zip(av, bv)),
                           default=0.0))
        else:
            d = max(d, abs(float(av) - float(bv)))
    return d


def _fresh_conn(port: int):
    import http.client
    return http.client.HTTPConnection("127.0.0.1", port, timeout=60)


def _run_json_leg(port: int, rows, n_requests: int):
    """One row per POST over a persistent connection — the pre-wire
    client shape. Returns (wall_s, latencies_ms, errors)."""
    lat = []
    errors = 0
    conn = _fresh_conn(port)
    t_start = time.perf_counter()
    i = done = 0
    while done < n_requests:
        body = json.dumps(rows[i % len(rows)]).encode()
        t0 = time.perf_counter()
        try:
            conn.request("POST", f"/score/{MODEL_ID}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
        except Exception:  # noqa: BLE001 — reconnect and retry the slot
            conn.close()
            conn = _fresh_conn(port)
            continue
        if resp.status == 503:
            time.sleep(min(float(resp.headers.get("Retry-After", 0.01)),
                           0.25))
            continue
        if resp.status != 200 or not payload:
            errors += 1
            i += 1
            continue
        lat.append((time.perf_counter() - t0) * 1e3)
        done += 1
        i += 1
    conn.close()
    return time.perf_counter() - t_start, lat, errors


def _run_binary_leg(port: int, rows, n_frames: int):
    """``ROWS_PER_FRAME`` rows per POST through the frame codec. The
    encode and reply-decode both run INSIDE the timed loop (the honest
    rows/s includes the codec), and their walls are split out per
    frame. Returns (wall_s, latencies_ms, rows_done, encode_ms,
    decode_ms, errors)."""
    from transmogrifai_tpu.serving import wireformat as wf

    lat = []
    enc_s = dec_s = 0.0
    rows_done = errors = 0
    conn = _fresh_conn(port)
    headers = {"Content-Type": wf.CONTENT_TYPE_FRAME}
    t_start = time.perf_counter()
    i = done = 0
    while done < n_frames:
        batch = [rows[(i * ROWS_PER_FRAME + j) % len(rows)]
                 for j in range(ROWS_PER_FRAME)]
        t_e = time.perf_counter()
        body = wf.encode_rows(MODEL_ID, batch)
        t0 = time.perf_counter()
        try:
            conn.request("POST", f"/score/{MODEL_ID}", body, headers)
            resp = conn.getresponse()
            payload = resp.read()
        except Exception:  # noqa: BLE001 — reconnect and retry the slot
            conn.close()
            conn = _fresh_conn(port)
            continue
        if resp.status == 503:
            time.sleep(min(float(resp.headers.get("Retry-After", 0.01)),
                           0.25))
            continue
        if resp.status != 200 or not payload:
            errors += 1
            i += 1
            continue
        t1 = time.perf_counter()
        reply = wf.decode_frame(payload)
        t_d = time.perf_counter()
        if reply.n_rows != len(batch):
            errors += 1
        else:
            rows_done += reply.n_rows
            done += 1
        lat.append((t1 - t0) * 1e3)
        enc_s += t0 - t_e
        dec_s += t_d - t1
        i += 1
    conn.close()
    wall = time.perf_counter() - t_start
    n = max(done, 1)
    return (wall, lat, rows_done, enc_s * 1e3 / n, dec_s * 1e3 / n,
            errors)


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import tempfile

    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    if os.environ.get("WIRE_EXPECT_ACCEL") == "1" and platform == "cpu":
        print(json.dumps({"metric": "wire_speed",
                          "error": "WIRE_EXPECT_ACCEL=1 but the backend "
                                   "initialized as cpu; refusing to "
                                   "record a CPU wall as an accelerator "
                                   "result"}))
        return 1

    from transmogrifai_tpu.scaleout.router import Router
    from transmogrifai_tpu.serving import FleetServer
    from transmogrifai_tpu.serving import wireformat as wf

    t0 = time.time()
    root = tempfile.mkdtemp(prefix="wire_zoo_")
    rows = _train(root)
    print(f"# trained {MODEL_ID} v1+v2 in {time.time() - t0:.1f}s on "
          f"{platform}", file=sys.stderr)

    # one padding bucket (min_bucket == max_batch): lanes warm with one
    # compile per program, and the compile-storm bound is tight
    fleet = FleetServer(max_batch=MAX_BATCH, max_wait_ms=2.0,
                        queue_capacity=4 * MAX_BATCH,
                        min_bucket=MAX_BATCH, shadow_rows=8,
                        metrics_port=0)
    fleet.register_dir(root)
    fleet.start(warmup_rows={MODEL_ID: rows[0]})
    fleet.prewarm(MODEL_ID, "v2", rows[0])
    port = fleet.metrics_http.port
    print(f"# fleet serving {MODEL_ID} (binary wire negotiated) at "
          f"127.0.0.1:{port}", file=sys.stderr)

    # -- parity: the same rows through both wires -----------------------
    parity_rows = rows[:PARITY_ROWS]
    conn = _fresh_conn(port)
    json_docs = []
    for r in parity_rows:
        conn.request("POST", f"/score/{MODEL_ID}",
                     json.dumps(r).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 200, doc
        doc.pop("traceId", None), doc.pop("lineage", None)
        json_docs.append(doc)
    conn.request("POST", f"/score/{MODEL_ID}",
                 wf.encode_rows(MODEL_ID, parity_rows),
                 {"Content-Type": wf.CONTENT_TYPE_FRAME})
    resp = conn.getresponse()
    payload = resp.read()
    assert resp.status == 200, payload[:300]
    frame_docs = wf.reply_to_rows(wf.decode_frame(payload))
    conn.close()
    parity = max(_diff(a, b) for a, b in zip(json_docs, frame_docs))
    print(f"# parity binary vs json over {PARITY_ROWS} rows: "
          f"{parity:.3g}", file=sys.stderr)

    # -- json vs binary legs (best-of-TRIALS, warm) ---------------------
    legs: dict = {}
    best = None
    for _ in range(TRIALS):
        wall, lat, errors = _run_json_leg(port, rows, JSON_REQUESTS)
        rps = len(lat) / max(wall, 1e-9)
        if errors:
            print(f"# json leg: {errors} errors", file=sys.stderr)
        if best is None or rps > best["rps"]:
            best = {"rps": round(rps, 1),
                    "p50_ms": round(float(np.percentile(lat, 50)), 3),
                    "p99_ms": round(float(np.percentile(lat, 99)), 3),
                    "requests": len(lat), "errors": int(errors)}
    legs["json"] = best
    print(f"# json: {best}", file=sys.stderr)

    best = None
    for _ in range(TRIALS):
        wall, lat, rows_done, enc_ms, dec_ms, errors = \
            _run_binary_leg(port, rows, FRAMES)
        rps = rows_done / max(wall, 1e-9)
        if errors:
            print(f"# binary leg: {errors} errors", file=sys.stderr)
        if best is None or rps > best["rps"]:
            best = {"rps": round(rps, 1),
                    "p50_ms": round(float(np.percentile(lat, 50)), 3),
                    "p99_ms": round(float(np.percentile(lat, 99)), 3),
                    "rows_per_frame": ROWS_PER_FRAME,
                    "frames": int(len(lat)), "rows": int(rows_done),
                    "encode_ms_per_frame": round(enc_ms, 4),
                    "decode_ms_per_frame": round(dec_ms, 4),
                    "errors": int(errors)}
    legs["binary"] = best
    print(f"# binary: {best}", file=sys.stderr)

    # -- through-router leg (both wires through the proxy hop) ----------
    router = Router(port=0, spill=0)
    router.set_replica("r0", port)
    router.start()
    rwall, rlat, rerr = _run_json_leg(router.port, rows,
                                      max(JSON_REQUESTS // 2, 50))
    router_json_rps = len(rlat) / max(rwall, 1e-9)
    (bwall, blat, brows, _, _, berr) = _run_binary_leg(
        router.port, rows, max(FRAMES // 2, 20))
    router_binary_rps = brows / max(bwall, 1e-9)
    router.stop()
    if rerr or berr:
        print(f"# router legs: {rerr} json / {berr} binary errors",
              file=sys.stderr)
    print(f"# router: json {router_json_rps:.0f} rows/s, binary "
          f"{router_binary_rps:.0f} rows/s", file=sys.stderr)

    # -- mid-run hot-swap under framed load -----------------------------
    swap_report: dict = {}
    client_out: dict = {}

    def swap_client():
        end_at = time.time() + SWAP_S
        lineages = []
        errors = total = 0
        conn = _fresh_conn(port)
        headers = {"Content-Type": wf.CONTENT_TYPE_FRAME}
        i = 0
        while time.time() < end_at:
            batch = [rows[(i * 16 + j) % len(rows)] for j in range(16)]
            try:
                conn.request("POST", f"/score/{MODEL_ID}",
                             wf.encode_rows(MODEL_ID, batch), headers)
                resp = conn.getresponse()
                payload = resp.read()
            except Exception:  # noqa: BLE001 — reconnect, retry the slot
                conn.close()
                conn = _fresh_conn(port)
                continue
            if resp.status == 503:
                time.sleep(0.01)
                continue
            total += 1
            if resp.status != 200:
                errors += 1
            else:
                try:
                    reply = wf.decode_frame(payload)
                    if reply.n_rows != len(batch):
                        errors += 1
                    lineages.append(
                        (time.time(),
                         (reply.meta.get("lineage") or {})
                         .get("version")))
                except wf.WireFormatError:
                    errors += 1
            i += 1
        conn.close()
        client_out.update(total=total, errors=errors, lineages=lineages)

    client = threading.Thread(target=swap_client)
    client.start()
    time.sleep(0.35 * SWAP_S)
    sw0 = time.time()
    try:
        swap_report.update(fleet.hot_swap(MODEL_ID, version="v2",
                                          tolerance=0.5))
        swap_report["promoted"] = "v2"
    except Exception as e:  # noqa: BLE001 — recorded in the artifact
        swap_report["promoted"] = ""
        swap_report["error"] = f"{type(e).__name__}: {e}"
    sw1 = time.time()
    client.join(timeout=SWAP_S + 120)

    post = [v for t, v in client_out.get("lineages", []) if t > sw1 + 0.2]
    post_lineage = post[-1] if post else ""
    zero_dropped = client_out.get("errors", 1) == 0 \
        and bool(client_out.get("total"))

    # -- compile-storm bound BEFORE stop --------------------------------
    lane = fleet.active_lanes()[MODEL_ID]
    storm = {str(b): n for b, n in lane.post_warmup_compiles().items()}
    storm_max = max(storm.values(), default=0)
    fleet.stop()

    baseline = _baseline_rps()
    ok = True
    notes = []
    if parity > 1e-5:
        ok = False
        notes.append(f"parity {parity} > 1e-5")
    if legs["binary"]["rps"] < 10.0 * baseline:
        ok = False
        notes.append(f"binary {legs['binary']['rps']} rows/s < 10x "
                     f"{baseline} baseline")
    if legs["binary"]["p99_ms"] > 5.0:
        ok = False
        notes.append(f"binary p99 {legs['binary']['p99_ms']}ms > 5ms")
    if legs["binary"]["rps"] <= legs["json"]["rps"]:
        ok = False
        notes.append("binary leg did not beat the json leg")
    if storm_max > 0:
        ok = False
        notes.append(f"compile storm: {storm}")
    if not zero_dropped:
        ok = False
        notes.append(f"swap client: {client_out.get('errors')} errors "
                     f"of {client_out.get('total')}")
    if swap_report.get("promoted") != "v2" or post_lineage != "v2":
        ok = False
        notes.append(f"swap: {swap_report}, post lineage "
                     f"{post_lineage!r}")

    artifact = {
        "metric": "wire_speed",
        "unit": "rows_per_s",
        "platform": platform,
        "requests": int(legs["json"]["requests"]
                        + legs["binary"]["frames"]
                        + client_out.get("total", 0)),
        "rows": int(legs["json"]["requests"] + legs["binary"]["rows"]),
        "train_rows": TRAIN_ROWS,
        "max_batch": MAX_BATCH,
        "baseline_fleet_http_rps": baseline,
        "json": legs["json"],
        "binary": legs["binary"],
        "router": {"json_rps": round(router_json_rps, 1),
                   "binary_rps": round(router_binary_rps, 1),
                   "spill": 0},
        "speedup_vs_json": round(legs["binary"]["rps"]
                                 / max(legs["json"]["rps"], 1e-9), 2),
        "speedup_vs_baseline": round(legs["binary"]["rps"]
                                     / max(baseline, 1e-9), 2),
        "parity_vs_json": float(f"{parity:.3g}"),
        "parity_rows": PARITY_ROWS,
        "compile_storm": {"max_post_warmup_per_bucket": int(storm_max),
                          "per_bucket": storm},
        "swap": {
            "promoted": swap_report.get("promoted", ""),
            "wall_s": swap_report.get("wallSeconds",
                                      round(sw1 - sw0, 6)),
            "zero_dropped": zero_dropped,
            "framed_requests": int(client_out.get("total", 0)),
            "post_swap_frames": len(post),
            "post_swap_lineage": post_lineage,
            "shadow_max_abs_diff": swap_report.get("shadowMaxAbsDiff"),
        },
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "WIRE_SPEED.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
