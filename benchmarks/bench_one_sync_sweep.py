"""One-sync sweep microbench (host-fetch fenced, whole-train walls).

Times a full AutoML ``train()`` — transmogrify + k-fold CV sweep over two
stacked linear families + winner refit + train/holdout evaluation — three
ways (round 9):

- ``per_family_settle`` — ``TRANSMOGRIFAI_SWEEP_ASYNC=0``: every family's
  metric batch is pulled as soon as it dispatches (the r08 behavior; one
  blocking host sync per family), cold refit.
- ``one_sync``          — the async dispatch/settle collapse: every
  family's stacked program launches before the first host sync, the whole
  sweep settles behind a single ``jax.block_until_ready``; cold refit.
- ``one_sync_warm``     — one-sync plus the stacked warm-started winner
  refit (fold-averaged init through the donated-buffer program).

The structural claims ride in the artifact and are schema-asserted by
``scripts/check_artifacts.py``: ``total_host_syncs.one_sync == 1`` (vs one
per family on the per-family path) from ``SweepCounters.run_to_json``, and
``refit_parity`` — the max |warm - cold| train/holdout metric delta —
within 1e-5 (the sweep is a converged convex regression, where the warm
init lands on the same optimum). The headline wall win is dispatch/settle
latency (families overlap on device; decisive on a tunneled TPU where
each settle is a round trip); on CPU the three walls are expected close.

Writes ``benchmarks/ONE_SYNC_SWEEP.json`` and prints one JSON line. Run:
``python benchmarks/bench_one_sync_sweep.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

ROWS = int(os.environ.get("SWEEP_ROWS", 60_000))
FOLDS = int(os.environ.get("SWEEP_FOLDS", 3))
D = int(os.environ.get("SWEEP_COLS", 8))       # raw feature columns
N_GRID = int(os.environ.get("SWEEP_GRID", 8))  # LinReg reg_param points
#: enough Adam steps that cold and fold-averaged-warm inits both converge
#: to the optimum of the (convex) squared loss — the refit-parity bound
#: in the artifact depends on it
MAX_ITER = int(os.environ.get("SWEEP_MAX_ITER", 400))
REPEATS = int(os.environ.get("SWEEP_REPEATS", 1))


def _build(frame_cls, ft, seed=0):
    rng = np.random.default_rng(seed)
    cols = {}
    X = rng.normal(size=(ROWS, D)).astype(np.float32)
    beta = rng.normal(size=D).astype(np.float32)
    y = X @ beta + 0.05 * rng.normal(size=ROWS).astype(np.float32)
    for j in range(D):
        cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
    cols["label"] = (ft.RealNN, y.tolist())
    return frame_cls.from_dict(cols)


def _train_once(frame):
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.extras import (
        OpGeneralizedLinearRegression,
    )
    from transmogrifai_tpu.models.linear import OpLinearRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        DataSplitter, RegressionModelSelector,
    )
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow
    UID.reset()
    feats = FeatureBuilder.from_frame(frame, response="label")
    label = feats.pop("label")
    vec = transmogrify(list(feats.values()), min_support=1)
    sel = RegressionModelSelector.with_cross_validation(
        n_folds=FOLDS, seed=1,
        models_and_parameters=[
            (OpLinearRegression(max_iter=MAX_ITER),
             [{"reg_param": r}
              for r in np.linspace(0.0, 0.2, N_GRID).round(6)]),
            (OpGeneralizedLinearRegression(max_iter=MAX_ITER),
             [{"reg_param": r} for r in (0.0, 0.1)]),
        ],
        splitter=DataSplitter(reserve_test_fraction=0.2, seed=1))
    pred = label.transform_with(sel, vec)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred).train())
    return model.selector_summary()


def _flat_metrics(summary) -> dict:
    out = {}
    for block in ("train_evaluation", "holdout_evaluation"):
        for ev_name, metrics in getattr(summary, block).items():
            for m, v in metrics.items():
                if isinstance(v, (int, float)) and v is not None:
                    out[f"{block}.{ev_name}.{m}"] = float(v)
    return out


def main() -> int:
    import jax
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.profiling import sweep_counters

    platform = jax.devices()[0].platform
    os.environ["TRANSMOGRIFAI_SWEEP_STACKED"] = "1"
    frame = _build(fr.HostFrame, ft)

    modes = {
        "per_family_settle": {"TRANSMOGRIFAI_SWEEP_ASYNC": "0",
                              "TRANSMOGRIFAI_REFIT_WARM": "0"},
        "one_sync": {"TRANSMOGRIFAI_SWEEP_ASYNC": "1",
                     "TRANSMOGRIFAI_REFIT_WARM": "0"},
        "one_sync_warm": {"TRANSMOGRIFAI_SWEEP_ASYNC": "1",
                          "TRANSMOGRIFAI_REFIT_WARM": "1"},
    }
    _train_once(frame)  # warmup: burn every mode-shared compile

    walls, syncs, summaries, runs = {}, {}, {}, {}
    for mode, env in modes.items():
        for k, v in env.items():
            os.environ[k] = v
        ts = []
        for _ in range(REPEATS):
            sweep_counters.reset()
            t0 = time.perf_counter()
            summaries[mode] = _train_once(frame)
            ts.append(time.perf_counter() - t0)
            runs[mode] = sweep_counters.run_to_json()
        walls[mode] = float(np.median(ts))
        syncs[mode] = runs[mode]["sweepHostSyncs"]
        for k in env:
            del os.environ[k]

    # parity: the sweep's validation metrics must be identical across
    # modes; the warm refit's train/holdout metrics within 1e-5 of cold
    val = {}
    for mode, s in summaries.items():
        val[mode] = {r.model_name: dict(r.metric_values)
                     for r in s.validation_results}
    v_par = 0.0
    for name in val["per_family_settle"]:
        for m in val["per_family_settle"][name]:
            for mode in ("one_sync", "one_sync_warm"):
                v_par = max(v_par, abs(val[mode][name][m]
                                       - val["per_family_settle"][name][m]))
    cold = _flat_metrics(summaries["one_sync"])
    warm = _flat_metrics(summaries["one_sync_warm"])
    r_par = max((abs(warm[k] - cold[k]) for k in cold), default=0.0)

    result = {
        "metric": "one_sync_sweep",
        "unit": "s",
        "platform": platform,
        "rows": ROWS, "cols": D, "folds": FOLDS,
        "grid_points": N_GRID + 2, "families": 2,
        "max_iter": MAX_ITER,
        "per_family_settle_s": round(walls["per_family_settle"], 3),
        "one_sync_s": round(walls["one_sync"], 3),
        "one_sync_warm_refit_s": round(walls["one_sync_warm"], 3),
        "speedup_vs_per_family": round(
            walls["per_family_settle"] / walls["one_sync"], 3),
        "total_host_syncs": {mode: int(s) for mode, s in syncs.items()},
        "async_families": runs["one_sync"]["asyncFamilies"],
        "refit_warm_starts": runs["one_sync_warm"]["refitWarmStarts"],
        "validation_parity": v_par,
        "refit_parity": r_par,
        "winner": summaries["one_sync"].best_model_name,
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ONE_SYNC_SWEEP.json")
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
