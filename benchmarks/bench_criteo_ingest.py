"""Criteo-shaped ingest + transmogrify benchmark.

SURVEY §6 / BASELINE.json name Criteo-1TB (13 numeric + 26 categorical
columns of click logs) as the pod-scale config. This bench builds the same
column shape synthetically at ``CRITEO_ROWS`` (default 10M) and times the
ingest-side hot path this repo optimized natively:

- text -> codes dictionary encoding (``native/dict_encode.cpp`` C++ pass;
  the pre-round-3 per-row Python loop is timed alongside for the record)
- bulk host -> device upload of the numeric block
- ``.transmogrify()`` vectorization of the full 39-column frame at a
  100k-row slice (the per-stage fit work; scaling it is the row-parallel
  mesh's job, measured by bench.py)

Prints one JSON line. Run: ``python benchmarks/bench_criteo_ingest.py``
(CRITEO_ROWS=200000 for a quick pass).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_ROWS = int(os.environ.get("CRITEO_ROWS", 10_000_000))
N_NUM, N_CAT = 13, 26
#: per-column cardinalities cycle through Criteo-like magnitudes
CARDS = [10, 100, 1000, 10_000, 100_000]


def synth_columns(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    nums = {f"i{j}": rng.normal(size=n).astype(np.float64)
            for j in range(N_NUM)}
    cats = {}
    for j in range(N_CAT):
        card = CARDS[j % len(CARDS)]
        codes = rng.integers(0, card, n)
        vals = np.array([f"c{j}_{v}" for v in range(card)], dtype=object)
        col = vals[codes]
        # Criteo columns carry missing values
        col[rng.uniform(size=n) < 0.05] = None
        cats[f"c{j}"] = col
    label = (rng.uniform(size=n) < 0.25).astype(np.float64)
    return nums, cats, label


def main() -> int:
    # site accelerator plugins (axon) override JAX_PLATFORMS at interpreter
    # start; re-assert the requested platform at config level before any
    # backend init (same dance as bench.py) so CPU runs don't touch a
    # possibly-hung TPU tunnel
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax
        try:
            jax.config.update("jax_platforms", want)
        except RuntimeError:
            pass
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.pipeline_data import PipelineData
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.utils.dict_encode import (
        _native, dict_encode, dict_encode_py,
    )

    t0 = time.time()
    nums, cats, label = synth_columns(N_ROWS)
    synth_s = time.time() - t0

    # --- dictionary encoding: native vs the old per-row Python loop ------
    t0 = time.time()
    encoded = {name: dict_encode(col) for name, col in cats.items()}
    encode_s = time.time() - t0
    total_uniques = sum(len(v) for _, v in encoded.values())

    py_rows = min(N_ROWS, 500_000)  # the old loop at full 10M would crawl
    t0 = time.time()
    # one column per cardinality class so the extrapolation isn't skewed
    # toward the cheap low-cardinality columns
    n_sampled = len(CARDS)
    for name in list(cats)[:n_sampled]:
        dict_encode_py(cats[name][:py_rows])
    python_encode_extrapolated_s = ((time.time() - t0)
                                    * (N_ROWS / py_rows)
                                    * (N_CAT / n_sampled))

    # the Criteo pain point is the HIGH-cardinality columns (hash-table
    # misses kill the Python dict loop there); time that class head-to-head
    hc = next(name for j, name in enumerate(cats)
              if CARDS[j % len(CARDS)] == max(CARDS))
    hc_rows = min(N_ROWS, 2_000_000)  # python dict cost grows with scale
    t0 = time.time()
    dict_encode(cats[hc][:hc_rows])
    hc_native_s = time.time() - t0
    t0 = time.time()
    dict_encode_py(cats[hc][:hc_rows])
    hc_python_s = time.time() - t0

    # --- frame build + device ingest ------------------------------------
    cols = {n_: fr.HostColumn(ft.Real, v, np.isfinite(v))
            for n_, v in nums.items()}
    for n_, v in cats.items():
        cols[n_] = fr.HostColumn(ft.PickList, v)
    cols["label"] = fr.HostColumn(ft.RealNN, label, np.ones(N_ROWS, bool))
    frame = fr.HostFrame(cols)

    t0 = time.time()
    data = PipelineData.from_host(frame)
    import jax
    data.device_col("i0")            # triggers the bulk numeric upload
    codes0 = data.device_col("c0")   # dictionary-encode + upload one cat
    jax.block_until_ready(codes0.codes)
    upload_s = time.time() - t0

    # --- transmogrify at a bounded slice ---------------------------------
    slice_rows = min(N_ROWS, 100_000)
    idx = np.arange(slice_rows)
    sl = fr.HostFrame({k: c.take(idx) for k, c in cols.items()})
    t0 = time.time()
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.dag import DagExecutor, compute_dag
    feats = FeatureBuilder.from_frame(sl, response="label")
    feats.pop("label")
    vec = transmogrify(list(feats.values()))
    out, _ = DagExecutor().fit_transform(
        PipelineData.from_host(sl), compute_dag([vec]))
    width = int(out.device_col(vec.name).values.shape[1])
    transmogrify_s = time.time() - t0

    print(json.dumps({
        "metric": "criteo_shape_ingest",
        "rows": N_ROWS,
        "columns": {"numeric": N_NUM, "categorical": N_CAT},
        "native_dict_encode": _native() is not None,
        "dict_encode_s": round(encode_s, 2),
        "dict_encode_rows_per_s": round(N_ROWS * N_CAT / encode_s),
        "python_loop_extrapolated_s": round(
            python_encode_extrapolated_s, 2),
        "speedup_vs_python_loop": round(
            python_encode_extrapolated_s / encode_s, 1),
        "high_cardinality_column": {
            "rows": hc_rows, "cardinality": max(CARDS),
            "native_s": round(hc_native_s, 2),
            "python_s": round(hc_python_s, 2),
            "speedup": round(hc_python_s / max(hc_native_s, 1e-9), 1)},
        "total_vocab": total_uniques,
        "numeric_upload_s": round(upload_s, 2),
        "transmogrify_rows": slice_rows,
        "transmogrify_s": round(transmogrify_s, 2),
        "transmogrify_width": width,
        "synth_s": round(synth_s, 2),
        "platform": jax.devices()[0].platform,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
