"""Line-rate explainability overhead: explained vs plain traffic through
the live serving fleet, with parity vs the offline LOCO path and a
mid-run hot-swap under explained load.

Topology: the main process trains one small binary AutoML endpoint
(``exp`` v1) plus a retrained candidate (v2), saves both in the
registry's versioned layout, and serves them through a
``serving.FleetServer`` built with ``explain=True`` — every lane gets a
``CompiledExplainer`` whose forward+LOCO program shares the scoring
lane's padding-bucket program cache. One HTTP client thread drives
closed-loop traffic over a persistent connection (identical client for
both legs, so the plain/explained comparison is apples to apples).

Measured and committed to ``benchmarks/EXPLAIN_OVERHEAD.json``:

- **plain vs explained rps + p50/p99** (best of ``EXPLAIN_TRIALS`` warm
  count-bounded trials each) and ``overhead_x`` = plain rps / explained
  rps — the measured price of "why this score" per request,
- **parity_vs_offline_loco**: max |served attribution - offline
  ``RecordInsightsLOCO`` delta| over ``PARITY_ROWS`` rows (acceptance
  <= 1e-5 in ``check_artifacts.py``) — the compiled serving path IS the
  offline semantics,
- **compile_storm**: post-warmup compiles per (lane, bucket) across BOTH
  lanes — 0 means steady-state explained traffic never recompiled,
- **swap**: a mid-run ``hot_swap`` to v2 under explained load — zero
  client-visible drops, and post-swap explained replies carry the
  promoted version's lineage stamp.

Platform honesty: the artifact records the measured backend verbatim;
``EXPLAIN_EXPECT_ACCEL=1`` makes a CPU fallback a hard error instead of
a mislabeled "accelerator" result.

Run: ``python benchmarks/bench_explain_overhead.py``. Knobs:
EXPLAIN_TRIALS, EXPLAIN_REQUESTS, EXPLAIN_TRAIN_ROWS, EXPLAIN_MAX_BATCH,
EXPLAIN_SWAP_S.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import threading
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

TRIALS = int(os.environ.get("EXPLAIN_TRIALS", 2))
REQUESTS = int(os.environ.get("EXPLAIN_REQUESTS", 400))
TRAIN_ROWS = int(os.environ.get("EXPLAIN_TRAIN_ROWS", 900))
MAX_BATCH = int(os.environ.get("EXPLAIN_MAX_BATCH", 32))
SWAP_S = float(os.environ.get("EXPLAIN_SWAP_S", 6.0))
PARITY_ROWS = 24
D_NUM = 6
MODEL_ID = "exp"


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_explain_overhead.py",
                "transmogrifai_tpu/serving/explain.py",
                "transmogrifai_tpu/serving/compiled.py",
                "transmogrifai_tpu/serving/server.py",
                "transmogrifai_tpu/serving/fleet.py",
                "transmogrifai_tpu/insights/loco.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train(root: str):
    """One endpoint (v1) + a retrained candidate (v2) in the versioned
    registry layout. Returns request rows."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.uid import UID
    from transmogrifai_tpu.workflow import Workflow

    def train(max_iter: int):
        UID.reset()  # versions of one endpoint share feature names
        rng = np.random.default_rng(11)
        n = TRAIN_ROWS
        X = rng.normal(size=(n, D_NUM))
        color = rng.choice(["red", "green", "blue"], size=n)
        logit = (1.4 * X[:, 0] - 0.9 * X[:, 1] + 0.4 * X[:, 2]
                 + 1.2 * (color == "red"))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
        cols = {"y": (ft.RealNN, y.tolist()),
                "color": (ft.PickList, color.tolist())}
        for j in range(D_NUM):
            cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
        frame = fr.HostFrame.from_dict(cols)
        feats = FeatureBuilder.from_frame(frame, response="y")
        features = transmogrify(
            [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
        sel = BinaryClassificationModelSelector \
            .with_train_validation_split(
                seed=1, models_and_parameters=[
                    (OpLogisticRegression(max_iter=max_iter), [{}])])
        pred = feats["y"].transform_with(sel, features)
        model = (Workflow().set_input_frame(frame)
                 .set_result_features(pred, features).train())
        rows = []
        for i in range(256):
            k = i % n
            row = {f"x{j}": float(X[k, j]) for j in range(D_NUM)}
            row["color"] = str(color[k])
            rows.append(row)
        return model, rows

    v1, rows = train(25)
    v1.save(os.path.join(root, MODEL_ID, "v1"))
    v2, _ = train(26)
    v2.save(os.path.join(root, MODEL_ID, "v2"))
    return rows


def _run_leg(port: int, rows, n_requests: int, explain: bool):
    """One closed-loop count-bounded client leg over a persistent
    connection. Returns (wall_s, latencies_ms, lineage_versions,
    errors, backpressure_retries)."""
    import http.client

    lat = []
    lineages = []
    errors = backpressure = 0
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    t_start = time.perf_counter()
    i = 0
    done = 0
    while done < n_requests:
        row = dict(rows[i % len(rows)])
        if explain:
            row["explain"] = True
        # bytes body: a str body ships in a second send() and can
        # stall ~40ms on Nagle + delayed ACK per request
        body = json.dumps(row).encode()
        t0 = time.perf_counter()
        try:
            conn.request("POST", f"/score/{MODEL_ID}", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = resp.read()
        except Exception:  # noqa: BLE001 — reconnect and retry the slot
            conn.close()
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            continue
        if resp.status == 503:
            backpressure += 1
            time.sleep(min(float(resp.headers.get("Retry-After", 0.01)),
                           0.25))
            continue
        if resp.status != 200 or not payload:
            errors += 1
            i += 1
            continue
        lat.append((time.perf_counter() - t0) * 1e3)
        doc = json.loads(payload)
        lineages.append((doc.get("lineage") or {}).get("version"))
        if explain and not doc.get("explanations"):
            errors += 1
        done += 1
        i += 1
    conn.close()
    return (time.perf_counter() - t_start, lat, lineages, errors,
            backpressure)


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import tempfile

    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    if os.environ.get("EXPLAIN_EXPECT_ACCEL") == "1" and platform == "cpu":
        print(json.dumps({"metric": "explain_overhead",
                          "error": "EXPLAIN_EXPECT_ACCEL=1 but the "
                                   "backend initialized as cpu; refusing "
                                   "to record a CPU wall as an "
                                   "accelerator result"}))
        return 1

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.insights.loco import RecordInsightsLOCO
    from transmogrifai_tpu.serving import FleetServer
    from transmogrifai_tpu.types.feature_types import nullable_base

    t0 = time.time()
    root = tempfile.mkdtemp(prefix="explain_zoo_")
    rows = _train(root)
    print(f"# trained {MODEL_ID} v1+v2 in {time.time() - t0:.1f}s on "
          f"{platform}", file=sys.stderr)

    # one padding bucket (min_bucket == max_batch): lanes warm with one
    # compile per fused program, and the compile-storm bound is tight
    fleet = FleetServer(max_batch=MAX_BATCH, max_wait_ms=2.0,
                        queue_capacity=4 * MAX_BATCH,
                        min_bucket=MAX_BATCH, shadow_rows=8,
                        metrics_port=0, explain=True, explain_top_k=8)
    fleet.register_dir(root)
    fleet.start(warmup_rows={MODEL_ID: rows[0]})
    fleet.prewarm(MODEL_ID, "v2", rows[0])
    port = fleet.metrics_http.port
    print(f"# fleet serving {MODEL_ID} (explain lane on) at "
          f"127.0.0.1:{port}", file=sys.stderr)

    # -- parity vs the offline RecordInsightsLOCO path ------------------
    v1 = fleet.registry.get(MODEL_ID, "v1").model
    pred_f = v1._prediction_feature()
    pstage = vec_name = None
    for t in v1.stages():
        if t.get_output() == pred_f:
            pstage, vec_name = t, t.runtime_input_names()[-1]
    parity_rows = rows[:PARITY_ROWS]
    cols = {}
    for f in v1.raw_features:
        ftype = nullable_base(f.ftype) if f.is_response else f.ftype
        cols[f.name] = fr.HostColumn.from_values(
            ftype, [r.get(f.name) for r in parity_rows])
    offline = RecordInsightsLOCO(model=pstage, top_k=500).host_apply(
        v1.transform(fr.HostFrame(cols)).host_col(vec_name)).values
    parity = 0.0
    n_groups = 0
    for i, row in enumerate(parity_rows):
        doc = fleet.submit_explain(MODEL_ID, dict(row),
                                   top_k=500).result(timeout=60)
        served = {e["name"]: e["delta"] for e in doc["explanations"]}
        n_groups = max(n_groups, len(served))
        ref = {k: float(v) for k, v in offline[i].items()}
        for name, delta in served.items():
            if name not in ref:
                parity = max(parity, abs(delta))  # offline dropped a 0
            else:
                parity = max(parity, abs(delta - ref[name]))
    print(f"# parity vs offline LOCO over {PARITY_ROWS} rows: "
          f"{parity:.3g} ({n_groups} groups served)", file=sys.stderr)

    # -- plain vs explained legs (best-of-TRIALS, warm) -----------------
    legs = {}
    for name, explain in (("plain", False), ("explained", True)):
        best = None
        for _ in range(TRIALS):
            wall, lat, _, errors, bp = _run_leg(port, rows, REQUESTS,
                                                explain)
            rps = len(lat) / max(wall, 1e-9)
            if errors:
                print(f"# {name} leg: {errors} errors", file=sys.stderr)
            if best is None or rps > best["rps"]:
                best = {"rps": round(rps, 1),
                        "p50_ms": round(float(np.percentile(lat, 50)), 3),
                        "p99_ms": round(float(np.percentile(lat, 99)), 3),
                        "requests": len(lat), "errors": int(errors),
                        "backpressure_retries": int(bp)}
        legs[name] = best
        print(f"# {name}: {best}", file=sys.stderr)
    overhead = legs["plain"]["rps"] / max(legs["explained"]["rps"], 1e-9)

    # -- mid-run hot-swap under explained load --------------------------
    swap_report: dict = {}
    client_out: dict = {}

    def swap_client():
        end_at = time.time() + SWAP_S
        lineages = []
        errors = total = 0
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        i = 0
        while time.time() < end_at:
            row = dict(rows[i % len(rows)])
            row["explain"] = True
            try:
                conn.request("POST", f"/score/{MODEL_ID}",
                             json.dumps(row).encode(),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
            except Exception:  # noqa: BLE001 — reconnect, retry the slot
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                continue
            if resp.status == 503:
                time.sleep(0.01)
                continue
            total += 1
            if resp.status != 200:
                errors += 1
            else:
                doc = json.loads(payload)
                if not doc.get("explanations"):
                    errors += 1
                lineages.append((time.time(),
                                 (doc.get("lineage") or {})
                                 .get("version")))
            i += 1
        conn.close()
        client_out.update(total=total, errors=errors, lineages=lineages)

    client = threading.Thread(target=swap_client)
    client.start()
    time.sleep(0.35 * SWAP_S)
    sw0 = time.time()
    try:
        swap_report.update(fleet.hot_swap(MODEL_ID, version="v2",
                                          tolerance=0.5))
        swap_report["promoted"] = "v2"
    except Exception as e:  # noqa: BLE001 — recorded in the artifact
        swap_report["promoted"] = ""
        swap_report["error"] = f"{type(e).__name__}: {e}"
    sw1 = time.time()
    client.join(timeout=SWAP_S + 120)

    post = [v for t, v in client_out.get("lineages", []) if t > sw1 + 0.2]
    post_lineage = post[-1] if post else ""
    zero_dropped = client_out.get("errors", 1) == 0 \
        and bool(client_out.get("total"))

    # -- compile-storm bound (both lanes) BEFORE stop -------------------
    lane = fleet.active_lanes()[MODEL_ID]
    storm = {"score": {str(b): n
                       for b, n in lane.post_warmup_compiles().items()},
             "explain": {str(b): n for b, n in
                         lane.post_warmup_explain_compiles().items()}}
    storm_max = max((n for per in storm.values() for n in per.values()),
                    default=0)
    explain_snap = lane.snapshot(mirror_to_profiler=False).get("explain")
    cache_doc = fleet.program_cache.to_json()
    fleet.stop()

    ok = True
    notes = []
    if parity > 1e-5:
        ok = False
        notes.append(f"parity {parity} > 1e-5")
    if storm_max > 0:
        ok = False
        notes.append(f"compile storm: {storm}")
    if not zero_dropped:
        ok = False
        notes.append(f"swap client: {client_out.get('errors')} errors "
                     f"of {client_out.get('total')}")
    if swap_report.get("promoted") != "v2" or post_lineage != "v2":
        ok = False
        notes.append(f"swap: {swap_report}, post lineage {post_lineage!r}")

    artifact = {
        "metric": "explain_overhead",
        "unit": "rps",
        "platform": platform,
        "requests": int(legs["plain"]["requests"]
                        + legs["explained"]["requests"]
                        + client_out.get("total", 0)),
        "train_rows": TRAIN_ROWS,
        "max_batch": MAX_BATCH,
        "groups": int(n_groups),
        "top_k": 8,
        "plain_rps": legs["plain"]["rps"],
        "explained_rps": legs["explained"]["rps"],
        "plain": legs["plain"],
        "explained": legs["explained"],
        "overhead_x": round(overhead, 3),
        "parity_vs_offline_loco": float(f"{parity:.3g}"),
        "parity_rows": PARITY_ROWS,
        "compile_storm": {"max_post_warmup_per_bucket": int(storm_max),
                          "per_lane": storm},
        "swap": {
            "promoted": swap_report.get("promoted", ""),
            "wall_s": swap_report.get("wallSeconds",
                                      round(sw1 - sw0, 6)),
            "zero_dropped": zero_dropped,
            "explained_requests": int(client_out.get("total", 0)),
            "post_swap_explained": len(post),
            "post_swap_lineage": post_lineage,
            "shadow_max_abs_diff": swap_report.get("shadowMaxAbsDiff"),
        },
        "explain_lane": {
            "maskChunk": (explain_snap or {}).get(
                "config", {}).get("maskChunk"),
            "batches": (explain_snap or {}).get(
                "batches", {}).get("count"),
        },
        "cache": cache_doc,
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "EXPLAIN_OVERHEAD.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
