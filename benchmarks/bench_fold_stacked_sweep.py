"""Fold-stacked ModelSelector sweep microbench (host-fetch fenced).

Times one linear-family (fold x grid) CV sweep unit — train every grid
point on every fold, score the validation folds, pull the metric batch —
at ``SWEEP_ROWS`` x 28, three ways:

- ``per_point``   — per-fold loop with sequential per-grid-point fits:
  the base ``Predictor.grid_fit_arrays`` contract (no batching at all).
- ``per_fold``    — per-fold loop with the family's grid-vmapped trainer
  and one metric host sync per fold: the pre-fold-stacking ``_sweep``
  fast path (r05 behavior).
- ``fold_stacked`` — this PR: all k folds x |grid| points as ONE compiled
  program via ``grid_fit_arrays_folds`` + the fold-batched metric, one
  dispatch and ONE host sync for the whole family.

Writes ``benchmarks/FOLD_STACKED_SWEEP.json`` and prints one JSON line.
The stacked path's headline win is dispatch/host-sync latency (k x fewer
round trips — decisive on a tunneled TPU); on CPU the win comes from
batching the per-point programs, so the honest CPU ratio to watch is
``speedup_vs_per_point`` (the unbatched estimator contract). Run:
``python benchmarks/bench_fold_stacked_sweep.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

ROWS = int(os.environ.get("SWEEP_ROWS", 100_000))
FOLDS = int(os.environ.get("SWEEP_FOLDS", 3))
#: transmogrified feature width — one-hot/hashed expansions land real
#: AutoML matrices near this, and it is where the per-point loop's
#: repeated X reads dominate (at the HIGGS bench's raw d=28 the loop is
#: bound by per-candidate intermediates instead and the gap narrows)
D = int(os.environ.get("SWEEP_COLS", 128))
REPEATS = int(os.environ.get("SWEEP_REPEATS", 1))
#: a 16-point elastic-net LR sweep: L1 grid points take the first-order
#: Adam path (the Newton shortcut covers only pure-L2 binary), so every
#: point trains the full ``max_iter`` scan — the shape where the
#: fold x grid batching matters and a real AutoML elastic-net sweep runs
N_GRID = int(os.environ.get("SWEEP_GRID", 16))


def main() -> int:
    import jax
    import jax.numpy as jnp
    from transmogrifai_tpu.evaluators.binary import (
        OpBinaryClassificationEvaluator,
    )
    from transmogrifai_tpu.models.base import Predictor
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.selector.validator import OpCrossValidation

    platform = jax.devices()[0].platform
    grid = [{"reg_param": r, "elastic_net_param": 0.5}
            for r in np.linspace(0.0, 0.2, N_GRID).round(6)]
    est = OpLogisticRegression()  # default max_iter=200
    ev = OpBinaryClassificationEvaluator()

    rng = np.random.default_rng(0)
    Xh = rng.normal(size=(ROWS, D)).astype(np.float32)
    logits = 1.2 * Xh[:, 0] - 0.7 * Xh[:, 1] + 0.5 * Xh[:, 2] * Xh[:, 3]
    yh = (rng.uniform(size=ROWS) < 1.0 / (1.0 + np.exp(-logits))
          ).astype(np.float32)
    X = jnp.asarray(Xh)
    y = jnp.asarray(yh)
    w = jnp.ones(ROWS, jnp.float32)
    tr_idx, va_idx = OpCrossValidation(n_folds=FOLDS).stacked_splits(ROWS)
    jtr, jva = jnp.asarray(tr_idx), jnp.asarray(va_idx)

    def per_point():
        """Per-fold loop, base-contract sequential per-point fits."""
        vals = []
        for f in range(FOLDS):
            Xtr, ytr, wtr = X[jtr[f]], y[jtr[f]], w[jtr[f]]
            models = Predictor.grid_fit_arrays(est, Xtr, ytr, wtr, grid)
            scores = est.grid_predict_scores(models, X[jva[f]])
            vals.append(ev.metric_batch_scores(y[jva[f]], scores, "auPR"))
        return np.stack(vals)

    def per_fold():
        """Per-fold loop, grid-vmapped family trainer (r05 fast path)."""
        vals = []
        for f in range(FOLDS):
            Xtr, ytr, wtr = X[jtr[f]], y[jtr[f]], w[jtr[f]]
            models = est.grid_fit_arrays(Xtr, ytr, wtr, grid)
            scores = est.grid_predict_scores(models, X[jva[f]])
            vals.append(ev.metric_batch_scores(y[jva[f]], scores, "auPR"))
        return np.stack(vals)

    def fold_stacked():
        """This PR: one fused stacked train+score + one fold-batched
        metric pull (the selector fast path's exact unit)."""
        Xtr = jnp.take(X, jtr, axis=0)
        ytr = jnp.take(y, jtr, axis=0)
        wtr = jnp.take(w, jtr, axis=0)
        scores = est.grid_scores_folds(Xtr, ytr, wtr, grid,
                                       jnp.take(X, jva, axis=0))
        return ev.metric_batch_scores_folds(jnp.take(y, jva, axis=0),
                                            scores, "auPR")

    def timed(fn):
        out0 = fn()  # warmup/compile burn; metric pulls fence the device
        ts = []
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts)), out0

    t_stacked, m_stacked = timed(fold_stacked)
    t_fold, m_fold = timed(per_fold)
    t_point, m_point = timed(per_point)
    parity = float(np.max(np.abs(np.asarray(m_stacked) - m_fold)))

    result = {
        "metric": f"linear_fold_grid_sweep_{ROWS}",
        "unit": "s",
        "platform": platform,
        "rows": ROWS, "cols": D, "folds": FOLDS, "grid_points": len(grid),
        "fold_stacked_s": round(t_stacked, 3),
        "per_fold_s": round(t_fold, 3),
        "per_point_s": round(t_point, 3),
        "speedup_vs_per_fold": round(t_fold / t_stacked, 2),
        "speedup_vs_per_point": round(t_point / t_stacked, 2),
        "metric_parity_stacked_vs_per_fold": parity,
    }
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "FOLD_STACKED_SWEEP.json")
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
