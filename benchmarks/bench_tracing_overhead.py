"""Request-tracing + flight-recorder hot-path overhead microbench.

Round 10 adds per-request trace context (trace-id mint at ingress,
carriage through the micro-batcher, batch-scope admit/batch/dispatch/
reply events into the flight recorder with durable JSONL spill). This
bench proves the cost on the serving throughput path stays within the
5% acceptance bound (`scripts/check_artifacts.py`, tracing_overhead):

- ``base``   — the PR 2/6 serving path: closed-loop submit through the
  ``ScoringServer``/``MicroBatcher``, NO trace context (the flight
  recorder is live but sees no traced requests, exactly a deployment
  that leaves tracing off).
- ``traced`` — the same path with a trace id minted per request and the
  flight recorder spilling JSONL, i.e. the full round-10 cost: id mint
  + per-pending carriage + batch-scope event emission + serialization.

Methodology — the signal is percent-scale and the noise is not: on a
small shared host, sustained-rps legs drift >20% run to run (CPU
frequency/neighbor states lasting seconds), so A-then-B whole-leg
comparisons measure the weather. Two countermeasures:

- **fine interleaving**: each trial alternates base/traced SLICES of
  ``TRACING_SLICE`` requests, so both modes sample the same machine
  states; per-mode time is the sum over slices. Spill leftovers drain
  in an untimed flush between slices (a traced slice's serialization
  must not bill the next base slice).
- **gc frozen + paused across the timed region**: a full gen-2 pass
  over the trained model + jax runtime costs ~40ms and lands on slices
  at random (a ~45% throughput lottery observed on 2 cores), and —
  worse — gen-2 passes scan the event RING the traced slices filled
  (maxlen tuples of member lists), so base slices get billed for
  traced state: cross-mode contamination, not hot-path cost. The gc is
  re-enabled and collected between trials, so allocation debt is paid,
  just never mid-measurement. (Long-lived serving daemons tune gc the
  same way — freeze after warmup is the standard deployment pattern.)

``overhead_pct`` is the median over ``TRIALS`` per-trial overheads —
reported alongside every trial so the spread is visible. The artifact
additionally proves the traced legs actually traced: events were
emitted, the spill holds lines, and one sampled trace id greps to its
batch -> dispatch -> reply events, from which the full admission ->
batch -> dispatch -> reply path reconstructs (serve.reply members carry
per-request latency, so admission time = reply ts - latencyMs).

Run: ``python benchmarks/bench_tracing_overhead.py``. Knobs:
TRACING_REQUESTS (per mode per trial), TRACING_SLICE,
TRACING_MAX_BATCH, TRACING_TRAIN_ROWS, TRACING_TRIALS,
TRACING_MODEL (gbt|lr).
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

#: requests per leg: ~1-2s samples — the 0.3s samples a 4096-request leg
#: produces on this path swing >2x with scheduler noise, drowning the
#: percent-scale signal this bench exists to measure
REQUESTS = int(os.environ.get("TRACING_REQUESTS", 24576))
#: interleaving granularity (requests per timed slice)
SLICE = int(os.environ.get("TRACING_SLICE", 1024))
MAX_BATCH = int(os.environ.get("TRACING_MAX_BATCH", 256))
TRAIN_ROWS = int(os.environ.get("TRACING_TRAIN_ROWS", 3000))
TRIALS = int(os.environ.get("TRACING_TRIALS", 7))
D_NUM = int(os.environ.get("TRACING_NUM_FEATURES", 16))
MODEL = os.environ.get("TRACING_MODEL", "gbt")


def _code_fingerprint() -> str:
    h = hashlib.sha256()
    for rel in ("benchmarks/bench_tracing_overhead.py",
                "transmogrifai_tpu/serving/batcher.py",
                "transmogrifai_tpu/serving/server.py",
                "transmogrifai_tpu/utils/events.py",
                "transmogrifai_tpu/utils/tracing.py"):
        try:
            with open(os.path.join(REPO, rel), "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


def _train_model():
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.models.linear import OpLogisticRegression
    from transmogrifai_tpu.models.trees import OpGBTClassifier
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(11)
    n = TRAIN_ROWS
    X = rng.normal(size=(n, D_NUM))
    color = rng.choice(["red", "green", "blue", "teal"], size=n)
    logit = (1.3 * X[:, 0] - 0.8 * X[:, 1] + 1.1 * (color == "red"))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(float)
    cols = {"y": (ft.RealNN, y.tolist()),
            "color": (ft.PickList, color.tolist())}
    for j in range(D_NUM):
        cols[f"x{j}"] = (ft.Real, X[:, j].tolist())
    frame = fr.HostFrame.from_dict(cols)
    feats = FeatureBuilder.from_frame(frame, response="y")
    features = transmogrify(
        [feats[f"x{j}"] for j in range(D_NUM)] + [feats["color"]])
    candidate = (OpGBTClassifier(num_rounds=30, max_depth=3), [{}]) \
        if MODEL == "gbt" else \
        (OpLogisticRegression(max_iter=30), [{"reg_param": 0.01}])
    sel = BinaryClassificationModelSelector.with_train_validation_split(
        seed=1, models_and_parameters=[candidate])
    pred = feats["y"].transform_with(sel, features)
    model = (Workflow().set_input_frame(frame)
             .set_result_features(pred, features).train())
    rows = []
    for i in range(REQUESTS):
        k = i % n
        row = {f"x{j}": float(X[k, j]) for j in range(D_NUM)}
        row["color"] = str(color[k])
        rows.append(row)
    return model, rows


def _drive(server, rows, mint) -> float:
    """One closed-loop leg: submit every row (flow control = block on the
    oldest in-flight future at backpressure), return rps. Deliberately
    does NO per-request bookkeeping beyond the product path itself — the
    grep-probe trace id is read back from the spill afterwards, so
    harness accounting can't bill the traced leg."""
    import collections

    from transmogrifai_tpu.serving import BackpressureError

    outstanding = collections.deque()
    t0 = time.perf_counter()
    i = 0
    while i < len(rows):
        try:
            fut = server.submit(
                rows[i], trace_id=mint() if mint is not None else None)
        except BackpressureError:
            if outstanding:
                try:
                    outstanding.popleft().result(timeout=300)
                except Exception:  # noqa: BLE001 — a row error reports at collection
                    pass
            continue
        outstanding.append(fut)
        i += 1
    for fut in outstanding:
        try:
            fut.result(timeout=300)
        except Exception:  # noqa: BLE001
            pass
    return len(rows) / (time.perf_counter() - t0)


def main() -> int:
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    import gc
    import statistics

    import jax

    from transmogrifai_tpu.serving import ScoringServer
    from transmogrifai_tpu.utils.events import events
    from transmogrifai_tpu.utils.tracing import new_trace_id

    platform = jax.devices()[0].platform
    t0 = time.time()
    model, rows = _train_model()
    print(f"# trained in {time.time() - t0:.1f}s on {platform}",
          file=sys.stderr)

    spill_dir = tempfile.mkdtemp(prefix="transmogrifai_tracing_bench_")
    spill_path = os.path.join(spill_dir, "events.jsonl")
    server = ScoringServer(model, max_batch=MAX_BATCH, max_wait_ms=2.0,
                           queue_capacity=4 * MAX_BATCH)
    server.start(warmup_row=rows[0])
    emitted0 = events.emitted

    # one throwaway leg per mode first: jit/allocator warm state must not
    # land on whichever mode happens to run first
    _drive(server, rows[:MAX_BATCH * 4], None)
    _drive(server, rows[:MAX_BATCH * 4], new_trace_id)
    # park the trained model + jax runtime outside gc (see module
    # docstring); tracing's own garbage still pays gen-0/1 collection
    gc.collect()
    gc.freeze()

    n_slices = max(REQUESTS // SLICE, 1)
    slice_rows = rows[:SLICE]
    base_trials: list = []
    traced_trials: list = []
    overheads: list = []
    for k in range(TRIALS):
        t_base = t_traced = 0.0
        gc.collect()
        gc.disable()
        for s in range(n_slices):
            # counterbalanced pair order (BT, TB, BT, ...): drift inside
            # a pair would otherwise bill whichever mode runs second
            for mode in (("base", "traced") if s % 2 == 0
                         else ("traced", "base")):
                if mode == "base":
                    events.configure(spill_path=None)  # untimed flush
                    s0 = time.perf_counter()
                    _drive(server, slice_rows, None)
                    t_base += time.perf_counter() - s0
                else:
                    events.configure(spill_path=spill_path)
                    s0 = time.perf_counter()
                    _drive(server, slice_rows, new_trace_id)
                    t_traced += time.perf_counter() - s0
        gc.enable()
        base_trials.append(round(n_slices * SLICE / t_base, 1))
        traced_trials.append(round(n_slices * SLICE / t_traced, 1))
        overheads.append((t_traced - t_base) / t_base * 100.0)
        print(f"# trial {k}: base {base_trials[-1]:.0f} rps, traced "
              f"{traced_trials[-1]:.0f} rps, overhead "
              f"{overheads[-1]:+.2f}%", file=sys.stderr)
    events.flush()
    events.configure(spill_path=None)
    server.stop()
    gc.unfreeze()
    events_emitted = events.emitted - emitted0

    # the headline triple must be self-consistent: report the rps pair
    # OF the median-overhead trial, so overhead_pct is exactly what the
    # two headline rps fields imply (max-of-each-series would mix
    # unpaired trials and contradict the median). With an even trial
    # count the median interpolates, so take the nearest real trial.
    med = statistics.median(overheads)
    mid = min(range(len(overheads)),
              key=lambda i: abs(overheads[i] - med))
    overhead_pct = overheads[mid]
    base_rps = base_trials[mid]
    traced_rps = traced_trials[mid]

    # acceptance reconstruction: one traced request's id greps to its
    # batch -> dispatch -> reply events in the durable spill (admission
    # reconstructs from serve.reply's per-member latency). The probe id
    # is read back from a mid-spill fan-in record — the driver keeps no
    # id list of its own (see _drive)
    probe = None
    kinds = set()
    spill_lines = 0
    with open(spill_path) as fh:
        lines = fh.readlines()
    for line in lines[len(lines) // 2:]:
        if '"serve.batch"' in line:
            ids = json.loads(line).get("traceIds") or []
            if ids:
                probe = ids[len(ids) // 2]
                break
    for line in lines:
        spill_lines += 1
        if probe is not None and probe in line:
            kinds.add(json.loads(line).get("kind"))
    path_reconstructed = {"serve.batch", "serve.dispatch",
                          "serve.reply"} <= kinds
    import shutil
    shutil.rmtree(spill_dir, ignore_errors=True)

    ok = True
    notes = []
    if overhead_pct > 5.0:
        ok = False
        notes.append(f"tracing overhead {overhead_pct:.2f}% exceeds the "
                     "5% acceptance bound")
    if not path_reconstructed:
        ok = False
        notes.append(f"trace id {probe} did not grep to the full "
                     f"admit/batch/dispatch/reply path (saw {sorted(kinds)})")
    if events_emitted <= 0:
        ok = False
        notes.append("traced legs emitted no flight-recorder events")

    artifact = {
        "metric": "tracing_overhead",
        "unit": "rps",
        "platform": platform,
        "requests": REQUESTS,
        "slice": SLICE,
        "max_batch": MAX_BATCH,
        "train_rows": TRAIN_ROWS,
        "model": MODEL,
        "trials": TRIALS,
        "base_rps": base_rps,
        "base_trials_rps": base_trials,
        "traced_rps": traced_rps,
        "traced_trials_rps": traced_trials,
        "overhead_pct": round(overhead_pct, 3),
        "overhead_trials_pct": [round(o, 2) for o in overheads],
        "events_emitted": int(events_emitted),
        "spill_lines": spill_lines,
        "path_reconstructed": path_reconstructed,
        "ok": ok,
        "notes": notes,
        "code_fingerprint": _code_fingerprint(),
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ"),
    }
    out_path = os.path.join(HERE, "TRACING_OVERHEAD.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(artifact, fh, indent=1)
    os.replace(tmp, out_path)
    print(json.dumps(artifact))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
