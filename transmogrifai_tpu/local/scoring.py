"""Local (engine-free) scoring.

Parity: reference ``local/src/main/scala/com/salesforce/op/local/
OpWorkflowModelLocal.scala:43-126`` — compiles the fitted DAG into a plain
closure ``dict -> dict`` folding each stage's row-level path, no batch
engine involved. The contract tests assert local scoring == batch scoring
(the reference's OpTransformerSpec invariant).
"""

from __future__ import annotations

from typing import Any, Callable

from transmogrifai_tpu.types import feature_types as ft

__all__ = ["make_score_function"]


def make_score_function(model) -> Callable[[dict], dict]:
    """Returns ``score(row: {raw feature name: python value}) -> {result
    feature name: python value}``."""
    layers = model.dag
    raw_names = [f.name for f in model.raw_features]
    result = [(f.name, f.ftype) for f in model.result_features]

    # precompute per-stage wiring
    plan = []
    for layer in layers:
        for t in layer:
            plan.append((t, t.runtime_input_names(), t.get_output().name))

    def score(row: dict) -> dict:
        vals: dict[str, Any] = {n: row.get(n) for n in raw_names}
        for t, in_names, out_name in plan:
            vals[out_name] = t.transform_row(*(vals.get(n) for n in in_names))
        out = {}
        for name, ftype in result:
            v = vals.get(name)
            if issubclass(ftype, ft.OPVector) and v is not None:
                v = list(map(float, v))
            out[name] = v
        return out

    return score
