"""Local (engine-free) scoring.

Parity: reference ``local/src/main/scala/com/salesforce/op/local/
OpWorkflowModelLocal.scala:43-126`` — compiles the fitted DAG into a plain
closure ``dict -> dict`` folding each stage's row-level path, no batch
engine involved. The contract tests assert local scoring == batch scoring
(the reference's OpTransformerSpec invariant).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from transmogrifai_tpu.types import feature_types as ft

__all__ = ["make_score_function", "required_raw_keys", "check_row"]


def required_raw_keys(model) -> tuple[str, ...]:
    """Raw-feature keys a scoring row must carry: every non-response raw
    (responses are optional at scoring time, as in ``WorkflowModel._ingest``).
    A key present with value ``None`` is an explicit null and is fine — the
    type system models missingness; an ABSENT key is a malformed request."""
    return tuple(sorted(f.name for f in model.raw_features
                        if not f.is_response))


def check_row(row: dict, required: Sequence[str]) -> None:
    """Raise ``KeyError`` naming every missing raw-feature key in ``row``.

    Serving admission control calls this at the door (before a request is
    queued) so malformed requests are rejected immediately instead of
    surfacing as silent ``None`` scores mid-batch."""
    missing = [n for n in required if n not in row]
    if missing:
        raise KeyError(
            f"scoring row lacks raw feature keys {missing}; required keys: "
            f"{list(required)}")


def make_score_function(model, strict: bool = False) -> Callable[[dict], dict]:
    """Returns ``score(row: {raw feature name: python value}) -> {result
    feature name: python value}``.

    With ``strict=True`` every call validates the row first: a missing
    non-response raw-feature key raises a ``KeyError`` naming the absent
    keys instead of silently scoring ``None``s. The returned closure also
    exposes ``required_keys`` and ``check_row(row)`` so admission-time
    validation (the online server) can reject without scoring."""
    layers = model.dag
    raw_names = [f.name for f in model.raw_features]
    required = required_raw_keys(model)
    result = [(f.name, f.ftype) for f in model.result_features]

    # precompute per-stage wiring
    plan = []
    for layer in layers:
        for t in layer:
            plan.append((t, t.runtime_input_names(), t.get_output().name))

    def score(row: dict) -> dict:
        if strict:
            check_row(row, required)
        vals: dict[str, Any] = {n: row.get(n) for n in raw_names}
        for t, in_names, out_name in plan:
            vals[out_name] = t.transform_row(*(vals.get(n) for n in in_names))
        out = {}
        for name, ftype in result:
            v = vals.get(name)
            if issubclass(ftype, ft.OPVector) and v is not None:
                v = list(map(float, v))
            out[name] = v
        return out

    score.required_keys = required
    score.check_row = lambda row: check_row(row, required)
    return score
