"""External-model import: serialized third-party models -> native scoring.

Parity: reference ``local/.../MLeapModelConverter.scala:93-160`` converts
foreign serialized models (MLeap bundles of Spark stages) into local scoring
functions. The TPU-native equivalents here convert the two lingua-franca
model interchange families into this framework's device models:

- ``import_xgboost_json``: an XGBoost ``save_model`` JSON booster ->
  :class:`TreeEnsembleModel` (binary logistic or squared-error regression).
- ``import_sklearn``: a fitted scikit-learn estimator (logistic/linear
  regression, gradient boosting, random forest, decision tree) -> the
  matching native model.

Both produce models that score on the SAME jitted device path as natively
trained ones (``models/trees.py`` binned complete-tree gathers /
``models/linear.py`` matmul), so imported models batch, jit, shard, and
serialize exactly like everything else.

Conversion notes (how foreign trees map onto the binned representation):

- Native trees are dense complete depth-D arrays over BINNED features:
  prediction gathers ``go_left = x_bin <= split_bin``. A foreign tree with
  float thresholds converts by collecting every threshold used per feature
  into that feature's bin-edge list, then rewriting each split's threshold
  as its edge INDEX. ``bin_data`` assigns ``x_bin = searchsorted(edges, x,
  'left')``, so ``x_bin <= b  <=>  x <= edges[b]``:
  sklearn routes left on ``x <= t`` (edge = t exactly) while XGBoost routes
  left on ``x < t`` (edge = nextafter(t, -inf), the largest float32 below
  t — exact float semantics, not an epsilon).
- Arbitrary topologies embed into the complete tree: absent/non-splitting
  nodes keep feature -1 (routes every row left), so a leaf at level L lands
  at dense-leaf slot ``pos << (D - L)`` down the all-left spine.
- XGBoost ``default_left`` (missing-value routing) is ignored: the
  transmogrification layer never emits NaN (nulls become indicator
  columns). NaN inputs would bin past every edge and route right.
- Dense depth-D arrays are 2^D leaves per tree: importing is refused above
  depth 16 (reference-scale models are <= 12; unbounded sklearn forests
  must be grown with ``max_depth`` set).
"""

from __future__ import annotations

import json
import math
import os
import numpy as np

from transmogrifai_tpu.models.linear import (
    LinearClassificationModel, LinearRegressionModel,
)
from transmogrifai_tpu.models.trees import TreeEnsembleModel

__all__ = ["import_xgboost_json", "import_sklearn"]

#: complete-tree representation is 2^depth leaves: refuse beyond this
_MAX_IMPORT_DEPTH = 16


# ---------------------------------------------------------------------------
# shared: foreign tree spec -> binned dense ensemble
# ---------------------------------------------------------------------------

class _TreeSpec:
    """One foreign tree in child-pointer form. ``feature[i] < 0`` marks a
    leaf whose output is ``value[i]``; internal nodes route left when
    ``x[feature] <= edge`` with ``edge`` already in inclusive-left form."""

    def __init__(self, feature, edge, left, right, value):
        self.feature = np.asarray(feature, np.int32)
        self.edge = np.asarray(edge, np.float32)
        self.left = np.asarray(left, np.int32)
        self.right = np.asarray(right, np.int32)
        self.value = np.asarray(value, np.float32)

    def depth(self) -> int:
        # iterative: an unbounded sklearn tree can out-recurse Python long
        # before the depth guard would fire
        best, stack = 0, [(0, 0)]
        while stack:
            node, level = stack.pop()
            if self.feature[node] < 0:
                best = max(best, level)
            else:
                stack.append((int(self.left[node]), level + 1))
                stack.append((int(self.right[node]), level + 1))
        return best


def _ensemble_from_specs(specs, *, kind: str, n_features: int,
                         learning_rate: float,
                         base_score) -> TreeEnsembleModel:
    """Build the dense binned ensemble from foreign tree specs.

    ``specs`` is either a flat list (binary/regression: one output) or a
    nested list ``[round][class]`` (multiclass: n_out trees per round —
    xgboost tree_info groups / sklearn per-class estimator columns).
    ``base_score`` may be a scalar or a per-class vector (sklearn
    multiclass GBM inits at the per-class prior log-odds)."""
    nested = bool(specs) and isinstance(specs[0], (list, tuple))
    grid = [list(row) for row in specs] if nested else [[s] for s in specs]
    n_rounds, n_out = len(grid), len(grid[0])
    flat = [s for row in grid for s in row]
    depth = max(max(s.depth() for s in flat), 1)
    if depth > _MAX_IMPORT_DEPTH:
        raise ValueError(
            f"imported tree depth {depth} exceeds {_MAX_IMPORT_DEPTH} "
            "(dense complete-tree representation; retrain the source model "
            "with a bounded max_depth)")
    # per-feature sorted unique edge lists -> rectangular [d, E] matrix
    per_feat: list[set] = [set() for _ in range(n_features)]
    for s in flat:
        for i in range(len(s.feature)):
            f = int(s.feature[i])
            if f >= 0:
                per_feat[f].add(np.float32(s.edge[i]))
    edge_lists = [np.asarray(sorted(es), np.float32) for es in per_feat]
    n_edges = max(max((len(e) for e in edge_lists), default=0), 1)
    pad = np.float32(np.finfo(np.float32).max)
    bin_edges = np.full((n_features, n_edges), pad, np.float32)
    for f, e in enumerate(edge_lists):
        bin_edges[f, :len(e)] = e

    n_leaves = 1 << depth
    feats = [np.full((n_rounds, n_out, 1 << lv), -1, np.int32)
             for lv in range(depth)]
    bins = [np.zeros((n_rounds, n_out, 1 << lv), np.int32)
            for lv in range(depth)]
    leaves = np.zeros((n_rounds, n_out, n_leaves), np.float32)

    for r, row in enumerate(grid):
        for c, s in enumerate(row):
            def embed(node: int, level: int, pos: int) -> None:
                if s.feature[node] < 0:
                    # all-left descent: feature stays -1 below, rows land
                    leaves[r, c, pos << (depth - level)] = s.value[node]
                    return
                f = int(s.feature[node])
                feats[level][r, c, pos] = f
                bins[level][r, c, pos] = int(
                    np.searchsorted(edge_lists[f], np.float32(s.edge[node])))
                embed(int(s.left[node]), level + 1, pos * 2)
                embed(int(s.right[node]), level + 1, pos * 2 + 1)
            embed(0, 0, 0)

    import jax.numpy as jnp
    base = (np.asarray(base_score, np.float32)
            if np.ndim(base_score) else float(base_score))
    model = TreeEnsembleModel(kind=kind, n_out=n_out,
                              learning_rate=float(learning_rate),
                              base_score=base, max_depth=depth)
    model.bin_edges = bin_edges
    model.trees = (tuple(jnp.asarray(f) for f in feats),
                   tuple(jnp.asarray(b) for b in bins),
                   jnp.asarray(leaves))
    return model


# ---------------------------------------------------------------------------
# XGBoost JSON
# ---------------------------------------------------------------------------

def import_xgboost_json(source) -> TreeEnsembleModel:
    """Load an XGBoost ``Booster.save_model("....json")`` artifact.

    ``source`` is a file path, a JSON string, or the parsed dict. Supports
    ``binary:logistic`` (-> ``gbt_classifier``), ``multi:softprob`` /
    ``multi:softmax`` (per-class ``tree_info`` groups -> multiclass
    ``gbt_classifier``) and ``reg:squarederror`` (-> ``gbt_regressor``).
    Leaf weights in the artifact already include eta, so the imported
    model uses learning_rate 1.0; the stored ``base_score`` maps onto the
    margin through the objective's link (logit for binary:logistic,
    identity for multiclass — a uniform per-class margin is
    softmax-invariant — and for regression).
    """
    if isinstance(source, dict):
        doc = source
    elif isinstance(source, os.PathLike) \
            or (isinstance(source, str)
                and not source.lstrip().startswith("{")):
        with open(source) as fh:  # missing path -> FileNotFoundError
            doc = json.load(fh)
    else:
        doc = json.loads(source)
    learner = doc["learner"]
    objective = learner["objective"]["name"]
    booster = learner["gradient_booster"]
    if booster.get("name", "gbtree") not in ("gbtree", ""):
        raise NotImplementedError(
            f"unsupported booster {booster.get('name')!r} "
            "(only gbtree imports)")
    gb_model = booster["model"]
    tree_info = [int(t) for t in gb_model.get("tree_info", [])]
    n_features = int(learner["learner_model_param"]["num_feature"])
    num_class = int(learner["learner_model_param"].get("num_class", "0"))
    base_raw = float(learner["learner_model_param"]["base_score"])
    if objective == "binary:logistic":
        kind = "gbt_classifier"
        p = min(max(base_raw, 1e-15), 1 - 1e-15)
        base = math.log(p / (1.0 - p))
    elif objective in ("multi:softprob", "multi:softmax"):
        # per-iteration class groups; the uniform base margin is
        # softmax-invariant, so probabilities match exactly (raw margins
        # carry the same constant shift xgboost applies)
        kind = "gbt_classifier"
        base = base_raw
    elif objective in ("reg:squarederror", "reg:linear"):
        kind = "gbt_regressor"
        base = base_raw
    else:
        raise NotImplementedError(
            f"unsupported objective {objective!r} (binary:logistic, "
            "multi:softprob/softmax and reg:squarederror import)")
    if num_class <= 1 and any(t != 0 for t in tree_info):
        raise NotImplementedError(
            "grouped tree_info without num_class (boosted random forests / "
            "non-class groups) not supported")

    specs = []
    for tree in gb_model["trees"]:
        if any(int(t) != 0 for t in tree.get("split_type", ())) \
                or tree.get("categories_nodes"):
            raise NotImplementedError(
                "categorical splits (enable_categorical boosters) encode "
                "category-set partitions, not numeric thresholds — only "
                "numeric-split boosters import")
        left = np.asarray(tree["left_children"], np.int32)
        right = np.asarray(tree["right_children"], np.int32)
        cond = np.asarray(tree["split_conditions"], np.float32)
        feat = np.asarray(tree["split_indices"], np.int32)
        is_leaf = left < 0
        # leaves: split_conditions holds the leaf weight; mark feature -1.
        # internal: xgboost routes left on x < t -> inclusive edge is the
        # largest float32 strictly below t
        feature = np.where(is_leaf, -1, feat).astype(np.int32)
        edge = np.where(is_leaf, np.float32(0),
                        np.nextafter(cond, np.float32(-np.inf),
                                     dtype=np.float32))
        specs.append(_TreeSpec(feature, edge, left, right,
                               np.where(is_leaf, cond, np.float32(0))))
    if num_class > 1:
        if len(specs) % num_class:
            raise ValueError(
                f"{len(specs)} trees do not divide into {num_class} "
                "class groups")
        # tree_info assigns each tree its class; iterations are contiguous
        n_rounds = len(specs) // num_class
        by_round: list[list] = [[None] * num_class for _ in range(n_rounds)]
        seen = [0] * num_class
        for s, cls in zip(specs, tree_info):
            if not 0 <= cls < num_class or seen[cls] >= n_rounds:
                raise ValueError(
                    f"malformed tree_info: class {cls} out of range or "
                    f"over {n_rounds} rounds for num_class={num_class}")
            by_round[seen[cls]][cls] = s
            seen[cls] += 1
        if any(s is None for row in by_round for s in row):
            raise ValueError("tree_info class groups are unbalanced")
        specs = by_round
    return _ensemble_from_specs(specs, kind=kind, n_features=n_features,
                                learning_rate=1.0, base_score=base)


# ---------------------------------------------------------------------------
# scikit-learn
# ---------------------------------------------------------------------------

def _sk_tree_spec(tree, leaf_value) -> _TreeSpec:
    """sklearn ``tree_`` (routes left on x <= threshold: edge = threshold
    exactly) -> spec; ``leaf_value(node) -> float`` maps the value array."""
    n = tree.node_count
    feature = np.asarray(tree.feature, np.int32).copy()
    is_leaf = np.asarray(tree.children_left) < 0
    feature[is_leaf] = -1
    value = np.array([leaf_value(i) if is_leaf[i] else 0.0
                      for i in range(n)], np.float32)
    return _TreeSpec(feature, np.where(is_leaf, 0.0, tree.threshold),
                     tree.children_left, tree.children_right, value)


def _sk_dummy_init(est):
    """The GBM's init estimator, validated to be the default prior
    (Dummy*) or 'zero'. Custom init estimators produce a PER-ROW raw init
    (link of the init model's predictions) that no constant base_score
    can represent."""
    init = getattr(est, "init_", None)
    if init is None or init == "zero" or est.init == "zero":
        return None
    if not type(init).__name__.startswith("Dummy"):
        raise NotImplementedError(
            f"GBM with custom init estimator {type(init).__name__} has a "
            "per-row raw init; only the default prior init imports")
    return init


def _sk_gbt_base(est, is_classifier: bool) -> float:
    """Raw-prediction init of a fitted sklearn GBM: log-odds of the prior
    for classification, the constant/mean for regression ('zero' -> 0)."""
    init = _sk_dummy_init(est)
    if init is None:
        return 0.0
    if is_classifier:
        p = float(np.clip(init.class_prior_[1], 1e-15, 1 - 1e-15))
        return math.log(p / (1.0 - p))
    return float(np.ravel(init.constant_)[0])


def import_sklearn(est):
    """Convert a fitted scikit-learn estimator into the native model with
    the same scoring behavior (verified-parity families below, binary AND
    multiclass; anything else raises):

    - ``LogisticRegression`` -> :class:`LinearClassificationModel`
    - ``LinearRegression`` / ``Ridge`` / ``Lasso`` / ``ElasticNet``
      -> :class:`LinearRegressionModel`
    - ``GradientBoostingClassifier`` / ``GradientBoostingRegressor``
      -> :class:`TreeEnsembleModel` (gbt; multiclass as per-class tree
      columns with the centered-log-prior init)
    - ``RandomForestClassifier`` / ``RandomForestRegressor`` /
      ``DecisionTree*`` -> :class:`TreeEnsembleModel` (rf; a lone decision
      tree is a forest of one; multiclass as per-class probability trees)
    """
    name = type(est).__name__
    if name == "LogisticRegression":
        coef = np.asarray(est.coef_)
        if coef.shape[0] == 1:  # binary: margin -> 2-column softmax form
            d = coef.shape[1]
            W = np.zeros((d, 2))
            W[:, 1] = coef[0]
            b = np.array([0.0, float(est.intercept_[0])])
            return LinearClassificationModel(weights=W, intercept=b)
        # multinomial: predict_proba = softmax(X @ coef.T + intercept)
        return LinearClassificationModel(
            weights=coef.T.astype(np.float64),
            intercept=np.asarray(est.intercept_, np.float64))
    if name in ("LinearRegression", "Ridge", "Lasso", "ElasticNet"):
        coef = np.asarray(est.coef_, np.float64)
        if coef.ndim > 1 and coef.shape[0] != 1:
            raise NotImplementedError(
                "multi-output linear regression import is single-target "
                f"only (coef_ shape {coef.shape})")
        return LinearRegressionModel(
            weights=coef.ravel(),
            intercept=float(np.ravel(est.intercept_)[0]))
    if name == "GradientBoostingClassifier":
        if getattr(est, "loss", "log_loss") not in ("log_loss", "deviance"):
            # exponential loss maps margin->proba via expit(2*raw), not
            # the sigmoid the native gbt_classifier applies
            raise NotImplementedError(
                f"GradientBoostingClassifier loss {est.loss!r}: only "
                "log_loss imports with probability parity")
        if est.n_classes_ == 2:
            specs = [_sk_tree_spec(t.tree_,
                                   lambda i, tr=t.tree_: tr.value[i, 0, 0])
                     for t in est.estimators_[:, 0]]
            return _ensemble_from_specs(
                specs, kind="gbt_classifier",
                n_features=est.n_features_in_,
                learning_rate=float(est.learning_rate),
                base_score=_sk_gbt_base(est, True))
        # multiclass: per-class tree columns, raw = centered-log-prior
        # init + lr * per-class sums, proba = softmax(raw)
        init = _sk_dummy_init(est)
        if init is None:
            base = np.zeros(est.n_classes_)
        else:
            prior = np.clip(np.asarray(init.class_prior_, np.float64),
                            1e-15, None)
            base = np.log(prior) - np.mean(np.log(prior))
        specs = [[_sk_tree_spec(t.tree_,
                                lambda i, tr=t.tree_: tr.value[i, 0, 0])
                  for t in stage] for stage in est.estimators_]
        return _ensemble_from_specs(
            specs, kind="gbt_classifier", n_features=est.n_features_in_,
            learning_rate=float(est.learning_rate), base_score=base)
    if name == "GradientBoostingRegressor":
        specs = [_sk_tree_spec(t.tree_,
                               lambda i, tr=t.tree_: tr.value[i, 0, 0])
                 for t in est.estimators_[:, 0]]
        return _ensemble_from_specs(
            specs, kind="gbt_regressor", n_features=est.n_features_in_,
            learning_rate=float(est.learning_rate),
            base_score=_sk_gbt_base(est, False))
    if name in ("RandomForestClassifier", "DecisionTreeClassifier"):
        trees = [e.tree_ for e in est.estimators_] \
            if name == "RandomForestClassifier" else [est.tree_]
        if trees[0].value.shape[1] != 1:
            # multi-output (2D y) forests carry one class block PER output;
            # pk() reads output 0 only and would silently drop the rest
            raise NotImplementedError(
                "multi-output (2D-target) forest import not supported")
        n_cls = trees[0].value.shape[2]

        def pk(i, tr, k):  # leaf class-k probability (normalized counts)
            row = tr.value[i, 0, :]
            tot = float(row.sum())
            return float(row[k]) / tot if tot > 0 else 0.0

        if n_cls == 2:
            specs = [_sk_tree_spec(tr, lambda i, tr=tr: pk(i, tr, 1))
                     for tr in trees]
        else:
            # per-class probability trees sharing one structure: the
            # native rf path means per-class leaves then normalizes —
            # identical to sklearn's mean of per-tree probability vectors
            specs = [[_sk_tree_spec(tr, lambda i, tr=tr, k=k: pk(i, tr, k))
                      for k in range(n_cls)] for tr in trees]
        return _ensemble_from_specs(
            specs, kind="rf_classifier", n_features=est.n_features_in_,
            learning_rate=1.0, base_score=0.0)
    if name in ("RandomForestRegressor", "DecisionTreeRegressor"):
        trees = [e.tree_ for e in est.estimators_] \
            if name == "RandomForestRegressor" else [est.tree_]
        if trees[0].value.shape[1] != 1:
            # same silent-drop hazard as the classifier branch: 2D-target
            # forests store one value block per output
            raise NotImplementedError(
                "multi-output (2D-target) forest import not supported")
        specs = [_sk_tree_spec(tr, lambda i, tr=tr: tr.value[i, 0, 0])
                 for tr in trees]
        return _ensemble_from_specs(
            specs, kind="rf_regressor", n_features=est.n_features_in_,
            learning_rate=1.0, base_score=0.0)
    raise NotImplementedError(f"no import path for sklearn {name}")
