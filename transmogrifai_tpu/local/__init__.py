from transmogrifai_tpu.local.scoring import (
    check_row, make_score_function, required_raw_keys,
)
from transmogrifai_tpu.local.model_import import (
    import_sklearn, import_xgboost_json,
)

__all__ = ["make_score_function", "required_raw_keys", "check_row",
           "import_sklearn", "import_xgboost_json"]
