from transmogrifai_tpu.local.scoring import make_score_function

__all__ = ["make_score_function"]
