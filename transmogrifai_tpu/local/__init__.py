from transmogrifai_tpu.local.scoring import make_score_function
from transmogrifai_tpu.local.model_import import (
    import_sklearn, import_xgboost_json,
)

__all__ = ["make_score_function", "import_sklearn", "import_xgboost_json"]
