"""OpParams: JSON-loadable run configuration.

Parity: reference ``features/src/main/scala/com/salesforce/op/OpParams.scala``
— reader params (paths, key columns), per-stage parameter overrides applied
by stage class name or uid (reflected setter), model/metrics write locations,
and a custom params map. Applied by ``Workflow.set_parameters`` (the analog
of ``OpWorkflow.setStageParameters``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

__all__ = ["OpParams"]


@dataclass
class OpParams:
    reader_params: dict = field(default_factory=dict)   # name -> {path, ...}
    stage_params: dict = field(default_factory=dict)    # class/uid -> {param: value}
    model_location: Optional[str] = None
    metrics_location: Optional[str] = None
    score_location: Optional[str] = None
    custom_params: dict = field(default_factory=dict)

    @staticmethod
    def from_file(path: str) -> "OpParams":
        with open(path) as fh:
            return OpParams.from_json(json.load(fh))

    @staticmethod
    def from_json(d: dict) -> "OpParams":
        return OpParams(
            reader_params=d.get("readerParams", {}),
            stage_params=d.get("stageParams", {}),
            model_location=d.get("modelLocation"),
            metrics_location=d.get("metricsLocation"),
            score_location=d.get("scoreLocation"),
            custom_params=d.get("customParams", {}),
        )

    def to_json(self) -> dict:
        return {
            "readerParams": self.reader_params,
            "stageParams": self.stage_params,
            "modelLocation": self.model_location,
            "metricsLocation": self.metrics_location,
            "scoreLocation": self.score_location,
            "customParams": self.custom_params,
        }

    # -- application ---------------------------------------------------------
    def apply_to_reader(self, reader) -> list[str]:
        """Apply reader overrides (reference ``OpParams.scala`` readerParams:
        per-reader-type path/partitions/custom settings). Matched by reader
        class name (``CSVReader``) or ``"default"``; any entry key naming an
        existing reader attribute is set (``path``, ``key_col``,
        ``chunk_rows``...); ``customParams`` entries set attributes too.
        Returns a log of applied overrides."""
        if reader is None:
            return []
        applied = []
        # generic defaults first so the class-specific entry wins
        for key in ("default", type(reader).__name__):
            overrides = self.reader_params.get(key)
            if not overrides:
                continue
            items = {**{k: v for k, v in overrides.items()
                        if k != "customParams"},
                     **overrides.get("customParams", {})}
            for pname, value in items.items():
                if hasattr(reader, pname):
                    setattr(reader, pname, value)
                    applied.append(
                        f"{type(reader).__name__}.{pname}={value!r}")
        return applied

    def apply_to_stages(self, stages) -> list[str]:
        """Set overrides on matching stages (by class name or uid); returns
        a log of applied overrides."""
        applied = []
        for stage in stages:
            for key in (type(stage).__name__, stage.uid):
                overrides = self.stage_params.get(key)
                if not overrides:
                    continue
                for pname, value in overrides.items():
                    if hasattr(stage, pname):
                        setattr(stage, pname, value)
                        applied.append(f"{stage.uid}.{pname}={value!r}")
                    elif hasattr(stage, "params") and isinstance(
                            getattr(stage, "params"), dict) \
                            and pname in stage.params:
                        stage.params[pname] = value
                        applied.append(f"{stage.uid}.params[{pname}]={value!r}")
        return applied
