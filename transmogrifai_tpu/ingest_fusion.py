"""Device-resident ingest/FE fusion: double-buffered streaming ingest and
the fingerprint-keyed device-frame cache.

Round 14 (ROADMAP item 4): ingest + transmogrify were the last big
host-side phase on the training wall. Three pieces close it:

- ``dag.fuse_dag_program`` (see ``dag.py``) compiles every all-device run
  of fitted DAG levels into ONE jitted program over the HBM-resident
  columnar frame.
- :class:`ChunkPrefetcher` (here) overlaps host IO + decode for chunk N+1
  with chunk N's device FE program: a bounded background thread runs the
  decode function ahead of the consumer, waits are watchdog-armed
  (``utils/devicewatch.py`` — a hung decode autopsies like a hung device
  dispatch), and the consumer's blocked time is metered so the committed
  overlap ratio is measured, not asserted.
- :class:`DeviceFrameCache` (here) keys the uploaded device columns by the
  host frame's content fingerprint: a train-then-score or repeated
  ``train()`` session over identical host columns reuses the resident
  device frame instead of re-transferring (and re-dict-encoding) it.
  Entries drop under HBM pressure (``utils/resources.hbm_pressure_state``)
  or RSS pressure on stat-less backends.

Knobs: ``TRANSMOGRIFAI_FE_FUSED=1|0`` (fusion master gate, ``dag.py``),
``TRANSMOGRIFAI_PREFETCH_DEPTH`` (chunks decoded ahead; 0 disables the
background thread), ``TRANSMOGRIFAI_FRAME_CACHE=1|0`` and
``TRANSMOGRIFAI_FRAME_CACHE_ENTRIES`` (device-frame cache). See
docs/PIPELINE.md.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
import warnings
from typing import Any, Callable, Iterable, Iterator, Optional

from transmogrifai_tpu import frame as fr

__all__ = ["ChunkPrefetcher", "DeviceFrameCache", "prefetch_depth",
           "frame_cache_enabled"]

_SENTINEL = object()


def prefetch_depth() -> int:
    """Chunks the background decoder may run ahead of the consumer
    (``TRANSMOGRIFAI_PREFETCH_DEPTH``, default 2; 0 disables prefetch)."""
    try:
        return max(int(os.environ.get("TRANSMOGRIFAI_PREFETCH_DEPTH", "2")), 0)
    except ValueError:
        warnings.warn("TRANSMOGRIFAI_PREFETCH_DEPTH is not an int; using 2",
                      RuntimeWarning)
        return 2


def frame_cache_enabled() -> bool:
    return os.environ.get("TRANSMOGRIFAI_FRAME_CACHE", "1") != "0"


class ChunkPrefetcher:
    """Bounded background decode-ahead over an iterable of work items.

    ``fn(item)`` runs on ONE background thread (host-only work by
    contract: record decode, numpy column building — jax dispatch stays
    on the consumer thread so device program order is unchanged), at most
    ``depth`` results ahead of the consumer. Iterating the prefetcher
    yields results in input order; a decode error re-raises at the
    consumer's position, so failure semantics match the serial loop.

    Every consumer wait is armed under the dispatch watchdog (site
    ``ingest.prefetch``) and registered in the ``DispatchLedger`` — a
    wedged producer (NFS hang, poisoned decode) autopsies exactly like a
    wedged device dispatch instead of silently stalling the train loop.
    Metering: ``utils.profiling.ingest_counters`` gets one
    ``chunks_prefetched`` per decoded chunk, the background thread's busy
    seconds in ``decode_s``, and the consumer's blocked seconds in
    ``prefetch_wait_s`` (the overlap ratio's raw ingredients).
    """

    def __init__(self, items: Iterable[Any], fn: Callable[[Any], Any],
                 depth: Optional[int] = None, name: str = "ingest-prefetch"):
        self.depth = prefetch_depth() if depth is None else max(int(depth), 0)
        self._fn = fn
        self._items = iter(items)
        self._name = name
        self._queue: queue.Queue = queue.Queue(maxsize=max(self.depth, 1))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: True while the producer is INSIDE fn (decoding a known item).
        #: The consumer arms the stall watchdog only then: a wait on an
        #: idle upstream (a long-running file stream between arrivals) is
        #: healthy and must not fire hang autopsies.
        self._decoding = False
        #: consumer-side accounting (read by the bench's overlap ratio)
        self.decode_s = 0.0
        self.wait_s = 0.0
        self.chunks = 0

    # -- producer ------------------------------------------------------------
    def _produce(self) -> None:
        from transmogrifai_tpu.utils.faults import fault_point
        from transmogrifai_tpu.utils.profiling import ingest_counters
        from transmogrifai_tpu.utils.tracing import span
        try:
            for item in self._items:
                if self._stop.is_set():
                    return
                t0 = time.monotonic()
                self._decoding = True
                try:
                    fault_point("ingest.prefetch")
                    with span("ingest.prefetch", chunk=self.chunks):
                        result = self._fn(item)
                except BaseException as err:  # noqa: BLE001 — re-raised at the consumer
                    self._queue.put(("error", err))
                    return
                finally:
                    self._decoding = False
                dt = time.monotonic() - t0
                self.decode_s += dt
                self.chunks += 1
                ingest_counters.chunks_prefetched += 1
                ingest_counters.decode_s += dt
                self._queue.put(("ok", result))
            self._queue.put(("done", _SENTINEL))
        except BaseException as err:  # noqa: BLE001 — re-raised at the consumer
            try:
                self._queue.put(("error", err))
            except Exception:  # failure-ok: consumer gone; nothing to notify
                pass

    # -- consumer ------------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        if self.depth <= 0:
            # prefetch disabled: serial decode on the consumer thread,
            # same metering surface (decode_s ticks, overlap is 0)
            from transmogrifai_tpu.utils.faults import fault_point
            from transmogrifai_tpu.utils.tracing import span
            for item in self._items:
                t0 = time.monotonic()
                fault_point("ingest.prefetch")
                with span("ingest.prefetch", chunk=self.chunks):
                    result = self._fn(item)
                self.decode_s += time.monotonic() - t0
                self.chunks += 1
                yield result
            return
        from transmogrifai_tpu.utils import devicewatch as dw
        from transmogrifai_tpu.utils.profiling import ingest_counters
        self._thread = threading.Thread(
            target=self._produce, name=self._name, daemon=True)
        self._thread.start()
        try:
            while True:
                t0 = time.monotonic()
                got = None
                while got is None:
                    if self._decoding:
                        # the producer is mid-decode: a wedged fn is the
                        # hang this wait can actually suffer — arm the
                        # watchdog + ledger for the remainder of the wait
                        eid = dw.dispatch_ledger.register(
                            "ingest.prefetch", chunk=self.chunks)
                        try:
                            with dw.watchdog.guard("ingest.prefetch",
                                                   site="ingest.prefetch"):
                                got = self._queue.get()
                        finally:
                            dw.dispatch_ledger.complete(eid)
                    else:
                        # upstream idle (e.g. a long-running file stream
                        # between arrivals): waiting is healthy — poll
                        # UNGUARDED so no false stall autopsies fire
                        try:
                            got = self._queue.get(timeout=0.5)
                        except queue.Empty:
                            continue
                kind, payload = got
                waited = time.monotonic() - t0
                self.wait_s += waited
                ingest_counters.prefetch_wait_s += waited
                if kind == "done":
                    return
                if kind == "error":
                    raise payload
                yield payload
        finally:
            self.close()

    def close(self) -> None:
        """Stop the producer (idempotent). Drains the queue so a blocked
        ``put`` can observe the stop flag and exit."""
        self._stop.set()
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)


class DeviceFrameCache:
    """Fingerprint-keyed cache of uploaded device frames.

    One entry = the DEVICE state a ``PipelineData`` accumulated for a host
    frame: the raw device column dict (numeric/vector uploads), the text
    codes cache (dict-encode results), and the row mask. The entry holds a
    reference to the LIVE dicts of the PipelineData registered at ingest —
    columns uploaded lazily after registration (the bulk numeric path, the
    first text encode) land in the cached entry automatically, so the
    second train()/score() over the same bytes starts fully resident.

    Keys combine the host frame's content fingerprint
    (``frame.frame_fingerprint``) with the placement context (backend +
    mesh shape/devices): a cache built under one mesh never serves a
    differently-sharded session. Entries are LRU-bounded
    (``TRANSMOGRIFAI_FRAME_CACHE_ENTRIES``, default 2) and ALL drop when
    the device reports HBM pressure (``resources.hbm_pressure_state``) or,
    on stat-less backends, host RSS pressure — the cache is a freshness
    optimization, never a residency obligation.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "TRANSMOGRIFAI_FRAME_CACHE_ENTRIES", "2"))
            except ValueError:
                capacity = 2
        self.capacity = max(int(capacity), 1)
        self._entries: "collections.OrderedDict[tuple, dict]" = \
            collections.OrderedDict()
        #: column-identity memo: tuple((name, id(values), id(mask))) ->
        #: content fingerprint. Scoring consults ONLY this (O(columns));
        #: the O(rows) content hash is paid when a frame is REGISTERED
        #: (train()) — never per scored micro-batch, where a stream of
        #: distinct batches could otherwise pay a guaranteed-miss full
        #: hash (plus per-row reprs on text columns) per batch. Sound
        #: because HostColumn/HostFrame are immutable by contract: the
        #: same value-array objects imply the same bytes.
        self._ident_fp: "collections.OrderedDict[tuple, str]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def _ctx_key() -> tuple:
        import jax

        from transmogrifai_tpu.parallel import mesh as pmesh
        try:
            backend = jax.default_backend()
        except Exception:  # failure-ok: no backend -> host-only context
            backend = "none"
        ctx = pmesh.current_mesh()
        if ctx is None:
            return (backend, None)
        return (backend, (ctx.n_data, ctx.n_model,
                          tuple(d.id for d in ctx.mesh.devices.flat)))

    def _under_pressure(self) -> bool:
        from transmogrifai_tpu.utils import resources
        hbm = resources.hbm_pressure_state()
        if hbm["pressured"]:
            return True
        if hbm["hbmBytesLimit"] > 0:
            return False
        # stat-less backends (CPU) only: the host RSS budget stands in —
        # the "device" arrays live in host memory there (the statvfs +
        # /proc probe is skipped entirely when real HBM stats exist)
        return bool(resources.pressure_state()["rssPressure"])

    def _drop_all(self, reason: str) -> None:
        from transmogrifai_tpu.utils.events import events
        from transmogrifai_tpu.utils.profiling import ingest_counters
        if not self._entries:
            return
        n = len(self._entries)
        self._entries.clear()
        ingest_counters.frame_cache_drops += n
        events.emit("ingest.frame_cache_drop", entries=n, reason=reason)

    def nbytes(self) -> int:
        with self._lock:
            return sum(e["nbytes"] for e in self._entries.values())

    def entries(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    @staticmethod
    def _ident(frame: fr.HostFrame) -> tuple:
        return tuple(sorted(
            (n, id(frame[n].values),
             id(frame[n].mask) if frame[n].mask is not None else 0)
            for n in frame.names()))

    # -- the adopt seam ------------------------------------------------------
    def adopt(self, frame: fr.HostFrame, data, register: bool = True) -> Any:
        """Called at ingest with the fresh ``PipelineData``: on a hit,
        returns a NEW PipelineData over ``frame`` sharing the cached
        device state (no re-transfer); on a miss with ``register``,
        fingerprints and registers the fresh instance's live device dicts
        (the train seam). ``register=False`` is the SCORING seam: only
        the O(columns) identity memo is consulted — an unknown frame
        (every distinct streaming micro-batch) returns untouched without
        paying the O(rows) content hash."""
        from transmogrifai_tpu.pipeline_data import PipelineData
        from transmogrifai_tpu.utils.profiling import ingest_counters
        ident = self._ident(frame)
        with self._lock:
            content_fp = self._ident_fp.get(ident)
        if content_fp is None:
            if not register:
                return data
            content_fp = fr.frame_fingerprint(frame)
        fp = (content_fp, self._ctx_key())
        with self._lock:
            if self._under_pressure():
                self._drop_all("pressure")
                return data
            self._ident_fp[ident] = content_fp
            while len(self._ident_fp) > 4 * self.capacity:
                self._ident_fp.popitem(last=False)
            entry = self._entries.get(fp)
            if entry is not None:
                self._entries.move_to_end(fp)
                ingest_counters.frame_cache_reuses += 1
                out = PipelineData(frame, entry["device"],
                                   n_rows_logical=entry["n_logical"])
                # share the LIVE dicts: later lazy uploads keep warming
                # the cached entry for the next session
                out.device = entry["device"]
                out._codes_cache = entry["codes"]
                out._row_mask = entry["row_mask"]
                return out
            if not register:
                return data
            self._entries[fp] = {
                "device": data.device, "codes": data._codes_cache,
                "row_mask": data._row_mask,
                "n_logical": data._n_logical,
                "nbytes": sum(fr.device_col_nbytes(c)
                              for c in data.device.values()),
            }
            ingest_counters.frame_cache_stores += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
        return data
