from transmogrifai_tpu.evaluators.base import EvaluatorBase
from transmogrifai_tpu.evaluators.binary import (
    BinaryClassificationMetrics, OpBinaryClassificationEvaluator,
)
from transmogrifai_tpu.evaluators.multi import (
    MultiClassificationMetrics, OpMultiClassificationEvaluator,
)
from transmogrifai_tpu.evaluators.regression import (
    OpRegressionEvaluator, RegressionMetrics,
)
from transmogrifai_tpu.evaluators.extras import (
    BinaryClassificationBinMetrics, ForecastMetrics, OpBinScoreEvaluator,
    OpForecastEvaluator, OPLogLoss, SingleMetric,
)
from transmogrifai_tpu.evaluators.factories import CustomEvaluator, Evaluators

__all__ = [
    "EvaluatorBase",
    "BinaryClassificationMetrics", "OpBinaryClassificationEvaluator",
    "MultiClassificationMetrics", "OpMultiClassificationEvaluator",
    "OpRegressionEvaluator", "RegressionMetrics",
    "ForecastMetrics", "OpForecastEvaluator",
    "BinaryClassificationBinMetrics", "OpBinScoreEvaluator",
    "SingleMetric", "OPLogLoss",
    "CustomEvaluator", "Evaluators",
]
