"""Binary classification evaluator.

Parity: reference ``core/.../evaluators/OpBinaryClassificationEvaluator
.scala`` — Precision/Recall/F1/AuROC/AuPR/Error + TP/TN/FP/FN, plus a
threshold sweep (``BinaryThresholdMetrics``).

TPU-first: the whole metric bundle computes in one jitted program — a sort
by score + cumulative sums give the full ROC/PR curves (the analog of
Spark's ExtendedBinaryClassificationMetrics confusion-by-threshold), then
AuROC by trapezoid and AuPR by step-wise average precision.
"""

from __future__ import annotations

from dataclasses import dataclass, field
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase

__all__ = ["BinaryClassificationMetrics", "OpBinaryClassificationEvaluator",
           "binary_metrics_arrays"]


@dataclass(frozen=True)
class BinaryClassificationMetrics:
    precision: float
    recall: float
    f1: float
    au_roc: float
    au_pr: float
    error: float
    tp: float
    tn: float
    fp: float
    fn: float
    threshold_metrics: Optional[dict] = field(default=None, repr=False)


@jax.jit
def _binary_curves(y, score, yhat, w):
    n = y.shape[0]
    order = jnp.argsort(-score)
    ys, ss, ws = y[order], score[order], w[order]
    tp = jnp.cumsum(ys * ws)
    fp = jnp.cumsum((1.0 - ys) * ws)
    pos = jnp.maximum(tp[-1], 1e-12)
    neg = jnp.maximum(fp[-1], 1e-12)
    # Tie handling: a (fpr, tpr) point is only a curve vertex at the END of
    # a tie group. Map every index to its tie-group end so duplicated points
    # contribute zero width to the integrals (order-independent metrics).
    idx = jnp.arange(n)
    is_end = jnp.concatenate([ss[:-1] != ss[1:], jnp.ones(1, bool)])
    group_end = jax.lax.cummin(jnp.where(is_end, idx, n - 1), reverse=True)
    tpr = (tp / pos)[group_end]
    fpr = (fp / neg)[group_end]
    precision = (tp / jnp.maximum(tp + fp, 1e-12))[group_end]
    # AuROC: trapezoid from (0,0) through the curve
    fpr0 = jnp.concatenate([jnp.zeros(1), fpr])
    tpr0 = jnp.concatenate([jnp.zeros(1), tpr])
    au_roc = jnp.sum((fpr0[1:] - fpr0[:-1]) * (tpr0[1:] + tpr0[:-1]) * 0.5)
    # AuPR: step-wise average precision sum(P_i * dRecall_i)
    rec0 = jnp.concatenate([jnp.zeros(1), tpr])
    au_pr = jnp.sum(precision * (rec0[1:] - rec0[:-1]))
    # confusion at the model's decision (prediction column)
    tp5 = jnp.sum(w * yhat * y)
    fp5 = jnp.sum(w * yhat * (1.0 - y))
    tn5 = jnp.sum(w * (1.0 - yhat) * (1.0 - y))
    fn5 = jnp.sum(w * (1.0 - yhat) * y)
    return dict(au_roc=au_roc, au_pr=au_pr, tp=tp5, fp=fp5, tn=tn5, fn=fn5,
                thresholds=ss, tpr=tpr, fpr=fpr, precision_curve=precision)


@jax.jit
def _binary_scalars(y, score, yhat, w):
    """All scalar metrics as ONE [6] vector so the host pays a single
    device->host sync (scalar-by-scalar pulls round-trip per value on
    tunneled devices)."""
    c = _binary_curves(y, score, yhat, w)
    return jnp.stack([c["au_roc"], c["au_pr"], c["tp"], c["fp"], c["tn"],
                      c["fn"]])


def binary_metrics_arrays(y, score, w=None, yhat=None,
                          with_threshold_metrics: bool = False
                          ) -> BinaryClassificationMetrics:
    y = jnp.asarray(y, jnp.float32)
    score = jnp.asarray(score, jnp.float32)
    w = jnp.ones_like(y) if w is None else jnp.asarray(w, jnp.float32)
    yhat = (score >= 0.5).astype(jnp.float32) if yhat is None \
        else jnp.asarray(yhat, jnp.float32)
    au_roc_v, au_pr_v, tp, fp, tn, fn = np.asarray(
        _binary_scalars(y, score, yhat, w), np.float64)
    c = {"au_roc": au_roc_v, "au_pr": au_pr_v}
    if with_threshold_metrics:
        c = _binary_curves(y, score, yhat, w)
    precision = tp / (tp + fp) if tp + fp > 0 else 0.0
    recall = tp / (tp + fn) if tp + fn > 0 else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall > 0 else 0.0)
    total = tp + fp + tn + fn
    error = (fp + fn) / total if total > 0 else 0.0
    thr = None
    if with_threshold_metrics:
        # downsample the curve to <=100 threshold points (reference sweeps a
        # bounded threshold grid)
        n = c["thresholds"].shape[0]
        idx = np.unique(np.linspace(0, n - 1, min(100, n)).astype(int))
        thr = {
            "thresholds": np.asarray(c["thresholds"])[idx].tolist(),
            "tpr": np.asarray(c["tpr"])[idx].tolist(),
            "fpr": np.asarray(c["fpr"])[idx].tolist(),
            "precisionByThreshold": np.asarray(c["precision_curve"])[idx].tolist(),
        }
    return BinaryClassificationMetrics(
        precision=precision, recall=recall, f1=f1,
        au_roc=float(au_roc_v), au_pr=float(au_pr_v), error=error,
        tp=tp, tn=tn, fp=fp, fn=fn, threshold_metrics=thr)


#: threshold bins for the sweep's ranking metrics — O(1/4096) curve bias,
#: far below fold-to-fold variance, at O(n) scatter cost instead of the
#: exact path's O(n log^2 n) on-device sort (the sort dominated CV sweeps
#: at 1M rows)
_SWEEP_BINS = 4096


@functools.partial(jax.jit, static_argnames=("metric",))
def _metric_batch(y, scores, w, metric: str):
    """Validation metric for a whole candidate batch: [G, n] scores -> [G].
    One fused program — the selector's sweep never syncs per candidate.

    auROC/auPR compute from BINNED curves (score histogram + cumsum — the
    selection-grade approximation; final reported metrics go through the
    exact sorted path in evaluate_arrays). Decision metrics (Precision/
    Recall/F1/Error at margin 0) are pure weighted sums, no curves at all.
    """
    if metric in ("auROC", "auPR"):
        B = _SWEEP_BINS

        def one(s):
            lo, hi = jnp.min(s), jnp.max(s)
            b = jnp.clip(((s - lo) / jnp.maximum(hi - lo, 1e-12)
                          * (B - 1)).astype(jnp.int32), 0, B - 1)
            pos = jnp.zeros(B, jnp.float32).at[b].add(y * w)
            neg = jnp.zeros(B, jnp.float32).at[b].add((1.0 - y) * w)
            tp = jnp.cumsum(pos[::-1])      # descending threshold
            fp = jnp.cumsum(neg[::-1])
            P = jnp.maximum(tp[-1], 1e-12)
            N = jnp.maximum(fp[-1], 1e-12)
            tpr = tp / P
            fpr = fp / N
            fpr0 = jnp.concatenate([jnp.zeros(1), fpr])
            tpr0 = jnp.concatenate([jnp.zeros(1), tpr])
            if metric == "auROC":
                return jnp.sum((fpr0[1:] - fpr0[:-1])
                               * (tpr0[1:] + tpr0[:-1]) * 0.5)
            prec = tp / jnp.maximum(tp + fp, 1e-12)
            return jnp.sum(prec * (tpr0[1:] - tpr0[:-1]))

        return jax.vmap(one)(scores)

    yhat = (scores >= 0.0).astype(jnp.float32)        # [G, n]
    yw = (y * w)[None, :]
    nw = ((1.0 - y) * w)[None, :]
    tp = jnp.sum(yhat * yw, axis=1)
    fp = jnp.sum(yhat * nw, axis=1)
    fn = jnp.sum((1.0 - yhat) * yw, axis=1)
    tn = jnp.sum((1.0 - yhat) * nw, axis=1)
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    if metric == "Precision":
        return precision
    if metric == "Recall":
        return recall
    if metric == "F1":
        return 2 * precision * recall / jnp.maximum(precision + recall,
                                                    1e-12)
    return (fp + fn) / jnp.maximum(tp + fp + tn + fn, 1e-12)  # Error


@functools.partial(jax.jit, static_argnames=("metric",))
def _metric_batch_folds(y, scores, w, metric: str):
    """Fold-stacked metric batch: ``y [k, n]``, ``scores [k, G, n]`` ->
    ``[k, G]`` — the per-fold ``_metric_batch`` vmapped over the CV axis, so
    a whole family's (fold x grid) sweep pays exactly ONE host sync."""
    return jax.vmap(lambda yk, sk, wk: _metric_batch(yk, sk, wk, metric))(
        y, scores, w)


class OpBinaryClassificationEvaluator(EvaluatorBase):
    name = "binary classification"
    default_metric = "auPR"
    metric_directions = {
        "auPR": True, "auROC": True, "Precision": True, "Recall": True,
        "F1": True, "Error": False,
    }

    def __init__(self, with_threshold_metrics: bool = False):
        self.with_threshold_metrics = with_threshold_metrics

    def evaluate_arrays(self, y, pred_col, w=None) -> BinaryClassificationMetrics:
        # Rank by the raw score (margin) — Spark's evaluator semantics. For
        # probabilistic models prob is monotone in raw so AUC is identical;
        # for margin-only models (SVC) one-hot "probabilities" would collapse
        # the curve to a single operating point.
        raw = pred_col.raw_prediction
        prob = pred_col.probability
        if raw is not None and raw.ndim == 2 and raw.shape[1] >= 2:
            score = raw[:, 1] - raw[:, 0]
        elif prob is not None and prob.ndim == 2 and prob.shape[1] >= 2:
            score = prob[:, 1]
        else:
            score = pred_col.prediction
        return binary_metrics_arrays(
            y, score, w, yhat=pred_col.prediction,
            with_threshold_metrics=self.with_threshold_metrics)

    def metric_batch_scores(self, y, scores, metric=None, w=None) -> np.ndarray:
        """Batched sweep path: scores [G, n] are margins (decision at 0)."""
        y = jnp.asarray(y, jnp.float32)
        w = jnp.ones_like(y) if w is None else jnp.asarray(w, jnp.float32)
        return np.asarray(_metric_batch(y, jnp.asarray(scores, jnp.float32),
                                        w, metric or self.default_metric))

    def metric_batch_scores_folds_device(self, y, scores, metric=None,
                                         w=None):
        """Fold-stacked metric batch WITHOUT the host pull: returns the
        ``[k, G]`` metric values as a device array future. The one-sync
        sweep dispatches every family's metric program through this and
        settles them all behind a single ``jax.block_until_ready``."""
        y = jnp.asarray(y, jnp.float32)
        w = jnp.ones_like(y) if w is None else jnp.asarray(w, jnp.float32)
        return _metric_batch_folds(y, jnp.asarray(scores, jnp.float32), w,
                                   metric or self.default_metric)

    def metric_batch_scores_folds(self, y, scores, metric=None,
                                  w=None) -> np.ndarray:
        """Fold-stacked sweep path: ``y [k, n]`` per-fold labels, ``scores
        [k, G, n]`` margins -> ``[k, G]`` metric values, one host sync."""
        return np.asarray(self.metric_batch_scores_folds_device(
            y, scores, metric, w))
