"""Forecast, calibration-bin, and log-loss evaluators.

Parity targets:
- ``core/.../evaluators/OpForecastEvaluator.scala`` — SMAPE, SeasonalError,
  MASE over a seasonal-naive baseline with window ``seasonal_window``.
- ``core/.../evaluators/OpBinScoreEvaluator.scala`` — equi-width score bins
  between observed min/max score: per-bin average score, conversion rate,
  counts, plus overall Brier score.
- ``core/.../stages/impl/evaluator/OPLogLoss.scala`` — mean negative
  log-probability of the true class (binary + multiclass variants).

All three are vectorized JAX/NumPy reductions rather than RDD fold/reduce:
the per-row semigroup accumulations of the reference become segment_sum /
masked-mean kernels that XLA fuses into single passes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase

__all__ = [
    "ForecastMetrics", "OpForecastEvaluator",
    "BinaryClassificationBinMetrics", "OpBinScoreEvaluator",
    "SingleMetric", "OPLogLoss",
]


@dataclass(frozen=True)
class ForecastMetrics:
    smape: float
    seasonal_error: float
    mase: float
    # aliases matching the reference's metric casing
    @property
    def SMAPE(self):  # noqa: N802
        return self.smape

    @property
    def MASE(self):  # noqa: N802
        return self.mase


class OpForecastEvaluator(EvaluatorBase):
    """Forecast metrics on (label, prediction) sequences in row order.

    ``seasonal_error`` is the mean |y_t - y_{t+window}| over the first
    ``n - window`` rows (the seasonal-naive forecaster's error); MASE is the
    mean absolute error scaled by it. SMAPE uses the symmetric 2|y-yhat| /
    (|y|+|yhat|) form with zero-denominator rows contributing 0.
    """

    name = "forecast"
    default_metric = "SMAPE"
    metric_directions = {"SMAPE": False, "MASE": False, "SeasonalError": False}

    def __init__(self, seasonal_window: int = 1, max_items: int = 87660):
        if seasonal_window <= 0:
            raise ValueError("seasonal_window must be positive")
        if max_items <= 0:
            raise ValueError("max_items must be positive")
        self.seasonal_window = int(seasonal_window)
        self.max_items = int(max_items)

    def evaluate_arrays(self, y, pred_col, w=None) -> ForecastMetrics:
        y = jnp.asarray(y, jnp.float32)[: self.max_items]
        yhat = jnp.asarray(pred_col.prediction, jnp.float32)[: self.max_items]
        n = y.shape[0]
        win = self.seasonal_window
        abs_diff = jnp.abs(y - yhat)
        sum_abs = jnp.abs(y) + jnp.abs(yhat)
        smape_terms = jnp.where(sum_abs > 0, abs_diff / sum_abs, 0.0)
        smape = float(2.0 * jnp.sum(smape_terms) / n) if n > 0 else 0.0
        seasonal_limit = n - win
        if seasonal_limit > 0:
            seasonal_abs = jnp.sum(jnp.abs(y[:seasonal_limit] - y[win:]))
            seasonal_error = float(seasonal_abs / seasonal_limit)
        else:
            seasonal_error = float("nan") if n == 0 else 0.0
        mase_den = seasonal_error * n
        abs_sum = float(jnp.sum(abs_diff))
        if mase_den > 0:
            mase = abs_sum / mase_den
        else:
            # Deliberate deviation from the reference (which reports 0.0 here):
            # a nonzero-error forecast against a constant label series must not
            # rank as perfect under a smaller-is-better metric.
            mase = 0.0 if abs_sum == 0.0 else float("inf")
        return ForecastMetrics(smape=smape, seasonal_error=seasonal_error,
                               mase=mase)


@dataclass(frozen=True)
class BinaryClassificationBinMetrics:
    brier_score: float
    bin_size: float
    bin_centers: list = field(default_factory=list)
    number_of_data_points: list = field(default_factory=list)
    number_of_positive_labels: list = field(default_factory=list)
    average_score: list = field(default_factory=list)
    average_conversion_rate: list = field(default_factory=list)

    @staticmethod
    def empty() -> "BinaryClassificationBinMetrics":
        return BinaryClassificationBinMetrics(0.0, 0.0, [], [], [], [], [])


class OpBinScoreEvaluator(EvaluatorBase):
    """Score-calibration diagnostics over equi-width bins of P(class=1).

    Bin range spans [min(min_score, 0), max(max_score, 1)] — the reference
    folds the observed scores into a (1.0, 0.0) seed, so the range always
    covers [0, 1] and widens only if scores escape it.
    """

    name = "bin score"
    default_metric = "BrierScore"
    metric_directions = {"BrierScore": False}

    def __init__(self, num_of_bins: int = 100):
        if num_of_bins <= 0:
            raise ValueError("num_of_bins must be positive")
        self.num_of_bins = int(num_of_bins)

    def evaluate_arrays(self, y, pred_col, w=None) -> BinaryClassificationBinMetrics:
        score = pred_col.pos_score()
        y = jnp.asarray(y, jnp.float32)
        n = int(score.shape[0])
        if n == 0:
            return BinaryClassificationBinMetrics.empty()
        b = self.num_of_bins
        # one fused device program, one host pull (tunnel-latency convention,
        # see evaluators/binary.py:_binary_scalars)
        max_s = jnp.maximum(jnp.max(score), 1.0)
        min_s = jnp.minimum(jnp.min(score), 0.0)
        diff = max_s - min_s
        idx = jnp.clip(((score - min_s) / diff * b).astype(jnp.int32), 0, b - 1)
        pos = (y > 0).astype(jnp.float32)
        counts = jnp.zeros(b, jnp.float32).at[idx].add(jnp.ones_like(score))
        positives = jnp.zeros(b, jnp.float32).at[idx].add(pos)
        score_sums = jnp.zeros(b, jnp.float32).at[idx].add(score)
        brier = jnp.mean((score - y) ** 2)
        packed = np.asarray(jnp.concatenate(
            [counts, positives, score_sums, jnp.stack([brier, min_s, max_s])]))
        counts_np, positives_np, score_sums_np = (
            packed[:b], packed[b:2 * b], packed[2 * b:3 * b])
        brier_f, min_f, max_f = (float(x) for x in packed[3 * b:])
        diff_f = max_f - min_f
        safe = np.maximum(counts_np, 1.0)
        centers = [min_f + diff_f * i / b + diff_f / (2 * b) for i in range(b)]
        return BinaryClassificationBinMetrics(
            brier_score=brier_f,
            bin_size=diff_f / b,
            bin_centers=centers,
            number_of_data_points=counts_np.astype(int).tolist(),
            number_of_positive_labels=positives_np.astype(int).tolist(),
            average_score=(score_sums_np / safe).tolist(),
            average_conversion_rate=(positives_np / safe).tolist(),
        )


@dataclass(frozen=True)
class SingleMetric:
    name: str
    value: float


class OPLogLoss(EvaluatorBase):
    """Mean -log P(true class). Works for binary and multiclass predictions;
    the true-class probability is gathered from the probability matrix.
    """

    name = "logloss"
    default_metric = "logLoss"
    metric_directions = {"logLoss": False}

    def __init__(self, eps: float = 1e-15):
        self.eps = float(eps)

    def evaluate_arrays(self, y, pred_col, w=None) -> SingleMetric:
        y = np.asarray(y)
        if y.size == 0:
            raise ValueError("empty data: log loss cannot be calculated")
        prob = pred_col.probability
        yi = jnp.asarray(y, jnp.int32)
        if prob is not None and getattr(prob, "ndim", 1) == 2 and prob.shape[1] >= 2:
            p = jnp.take_along_axis(jnp.asarray(prob, jnp.float32),
                                    yi[:, None], axis=1)[:, 0]
        else:
            # (n,0)-probability models (margin-only / regression convention)
            p1 = pred_col.pos_score()
            p = jnp.where(yi > 0, p1, 1.0 - p1)
        val = float(jnp.mean(-jnp.log(jnp.clip(p, self.eps, 1.0))))
        return SingleMetric(name="logLoss", value=val)

    def metric_value(self, metrics, metric=None):
        return metrics.value

    @staticmethod
    def binary_log_loss() -> "OPLogLoss":
        return OPLogLoss()

    @staticmethod
    def multi_log_loss() -> "OPLogLoss":
        return OPLogLoss()
