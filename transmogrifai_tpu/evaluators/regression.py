"""Regression evaluator.

Parity: reference ``core/.../evaluators/OpRegressionEvaluator.scala`` —
RMSE/MSE/R2/MAE plus the signed-percentage-error histogram.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase

__all__ = ["RegressionMetrics", "OpRegressionEvaluator"]


@dataclass(frozen=True)
class RegressionMetrics:
    rmse: float
    mse: float
    r2: float
    mae: float
    signed_percentage_error_histogram: Optional[dict] = field(
        default=None, repr=False)


class OpRegressionEvaluator(EvaluatorBase):
    name = "regression"
    default_metric = "RMSE"
    metric_directions = {"RMSE": False, "MSE": False, "MAE": False, "R2": True}

    def __init__(self, with_error_histogram: bool = False,
                 histogram_bins: tuple = (-100.0, -50.0, -25.0, -10.0, 0.0,
                                          10.0, 25.0, 50.0, 100.0)):
        self.with_error_histogram = with_error_histogram
        self.histogram_bins = tuple(histogram_bins)

    def evaluate_arrays(self, y, pred_col, w=None) -> RegressionMetrics:
        y = jnp.asarray(y, jnp.float32)
        yhat = jnp.asarray(pred_col.prediction, jnp.float32)
        w = jnp.ones_like(y) if w is None else jnp.asarray(w, jnp.float32)
        wsum = jnp.maximum(jnp.sum(w), 1e-12)
        err = yhat - y
        mse = float(jnp.sum(w * err ** 2) / wsum)
        mae = float(jnp.sum(w * jnp.abs(err)) / wsum)
        ybar = jnp.sum(w * y) / wsum
        ss_tot = float(jnp.sum(w * (y - ybar) ** 2))
        ss_res = float(jnp.sum(w * err ** 2))
        r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
        hist = None
        if self.with_error_histogram:
            pct = np.asarray(100.0 * err / jnp.where(jnp.abs(y) < 1e-12, 1.0, y))
            counts, edges = np.histogram(pct, bins=np.asarray(self.histogram_bins))
            hist = {"binEdges": edges.tolist(), "counts": counts.tolist()}
        return RegressionMetrics(
            rmse=float(np.sqrt(mse)), mse=mse, r2=r2, mae=mae,
            signed_percentage_error_histogram=hist)

    def metric_batch_scores(self, y, preds, metric=None, w=None) -> np.ndarray:
        """Batched sweep path: preds [G, n] predictions -> metric per model."""
        metric = metric or self.default_metric
        y = jnp.asarray(y, jnp.float32)[None, :]
        preds = jnp.asarray(preds, jnp.float32)
        err = preds - y
        mse = jnp.mean(err ** 2, axis=1)
        if metric == "MSE":
            out = mse
        elif metric == "RMSE":
            out = jnp.sqrt(mse)
        elif metric == "MAE":
            out = jnp.mean(jnp.abs(err), axis=1)
        else:  # R2
            ss_tot = jnp.maximum(jnp.sum((y - jnp.mean(y)) ** 2), 1e-12)
            out = 1.0 - jnp.sum(err ** 2, axis=1) / ss_tot
        return np.asarray(out)

    def metric_batch_scores_folds_device(self, y, preds, metric=None,
                                         w=None):
        """Fold-stacked metric batch WITHOUT the host pull (``[k, G]``
        device array) — the one-sync sweep's dispatch unit; same row
        reductions as ``metric_batch_scores`` per fold lane."""
        metric = metric or self.default_metric
        y = jnp.asarray(y, jnp.float32)[:, None, :]   # [k, 1, n]
        preds = jnp.asarray(preds, jnp.float32)       # [k, G, n]
        err = preds - y
        mse = jnp.mean(err ** 2, axis=2)
        if metric == "MSE":
            out = mse
        elif metric == "RMSE":
            out = jnp.sqrt(mse)
        elif metric == "MAE":
            out = jnp.mean(jnp.abs(err), axis=2)
        else:  # R2
            ss_tot = jnp.maximum(
                jnp.sum((y - jnp.mean(y, axis=2, keepdims=True)) ** 2,
                        axis=2), 1e-12)               # [k, 1]
            out = 1.0 - jnp.sum(err ** 2, axis=2) / ss_tot
        return out

    def metric_batch_scores_folds(self, y, preds, metric=None,
                                  w=None) -> np.ndarray:
        """Fold-stacked sweep path: ``y [k, n]`` per-fold labels, ``preds
        [k, G, n]`` -> ``[k, G]`` metric values, one host sync."""
        return np.asarray(self.metric_batch_scores_folds_device(
            y, preds, metric, w))
