"""Multiclass classification evaluator.

Parity: reference ``core/.../evaluators/OpMultiClassificationEvaluator.scala``
— weighted Precision/Recall/F1/Error plus top-K accuracy and the per-class
confusion summary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase

__all__ = ["MultiClassificationMetrics", "OpMultiClassificationEvaluator"]


@dataclass(frozen=True)
class MultiClassificationMetrics:
    precision: float        # weighted by class support
    recall: float
    f1: float
    error: float
    top_k_accuracy: tuple = ()
    confusion: Optional[list] = field(default=None, repr=False)


class OpMultiClassificationEvaluator(EvaluatorBase):
    name = "multiclass classification"
    default_metric = "F1"
    metric_directions = {"Precision": True, "Recall": True, "F1": True,
                         "Error": False}

    def __init__(self, top_ks: tuple = (1, 3), with_confusion: bool = False):
        self.top_ks = tuple(top_ks)
        self.with_confusion = with_confusion

    def evaluate_arrays(self, y, pred_col, w=None) -> MultiClassificationMetrics:
        y = np.asarray(y).astype(np.int64)
        yhat = np.asarray(pred_col.prediction).astype(np.int64)
        w = np.ones_like(y, dtype=np.float64) if w is None else np.asarray(w)
        prob = np.asarray(pred_col.probability)
        n_cls = max(int(y.max()), int(yhat.max())) + 1 if y.size else 1
        conf = np.zeros((n_cls, n_cls))
        np.add.at(conf, (y, yhat), w)
        support = conf.sum(axis=1)
        pred_count = conf.sum(axis=0)
        diag = np.diag(conf)
        prec_c = np.divide(diag, pred_count, out=np.zeros(n_cls),
                           where=pred_count > 0)
        rec_c = np.divide(diag, support, out=np.zeros(n_cls),
                          where=support > 0)
        f1_c = np.divide(2 * prec_c * rec_c, prec_c + rec_c,
                         out=np.zeros(n_cls), where=(prec_c + rec_c) > 0)
        wsum = max(support.sum(), 1e-12)
        precision = float((prec_c * support).sum() / wsum)
        recall = float((rec_c * support).sum() / wsum)
        f1 = float((f1_c * support).sum() / wsum)
        error = 1.0 - float(diag.sum() / wsum)
        topks = []
        if prob.size and prob.shape[1] > 1:
            order = np.argsort(-prob, axis=1)
            for k in self.top_ks:
                hit = (order[:, :k] == y[:, None]).any(axis=1)
                topks.append(float((hit * w).sum() / wsum))
        return MultiClassificationMetrics(
            precision=precision, recall=recall, f1=f1, error=error,
            top_k_accuracy=tuple(topks),
            confusion=conf.tolist() if self.with_confusion else None)
