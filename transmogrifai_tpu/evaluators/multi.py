"""Multiclass classification evaluator.

Parity: reference ``core/.../evaluators/OpMultiClassificationEvaluator.scala``
(641 LoC) — weighted Precision/Recall/F1/Error plus the four deep metric
families:

- **threshold metrics** (``calculateThresholdMetrics:398-486``): per topN,
  correct/incorrect/no-prediction counts at every confidence threshold —
  "correct" means the true class is in the model's topN AND its probability
  clears the threshold; "no prediction" means even the max probability
  doesn't.
- **topK metrics** (``calculateTopKMetrics:352-380``): weighted P/R/F1/error
  restricted to the K most frequent labels (rarer true labels relabeled to
  an out-of-set class, so predictions hitting them count as wrong).
- **confusion-by-threshold** (``calculateConfMatrixMetricsByThreshold``):
  flattened confusion matrices over the top ``conf_matrix_num_classes``
  labels, one per confidence threshold (rows with max-prob below drop out).
- **misclassification report** (``calculateMisClassificationMetrics``): per
  label (and per prediction) category, total/correct counts plus the top
  ``conf_matrix_min_support`` misclassified counterparts.

All counts vectorize as numpy histogram/confusion passes — no per-row
Python in the hot path (the RDD treeAggregate analog is a bincount).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase

__all__ = ["MultiClassificationMetrics", "MulticlassThresholdMetrics",
           "OpMultiClassificationEvaluator"]


@dataclass(frozen=True)
class MulticlassThresholdMetrics:
    top_ns: tuple
    thresholds: tuple
    correct_counts: dict            # topN -> [n_thresholds]
    incorrect_counts: dict
    no_prediction_counts: dict

    def to_json(self) -> dict:
        return {
            "topNs": list(self.top_ns),
            "thresholds": list(self.thresholds),
            "correctCounts": {str(k): list(map(int, v))
                              for k, v in self.correct_counts.items()},
            "incorrectCounts": {str(k): list(map(int, v))
                                for k, v in self.incorrect_counts.items()},
            "noPredictionCounts": {str(k): list(map(int, v))
                                   for k, v in
                                   self.no_prediction_counts.items()},
        }


@dataclass(frozen=True)
class MultiClassificationMetrics:
    precision: float        # weighted by class support
    recall: float
    f1: float
    error: float
    top_k_accuracy: tuple = ()
    confusion: Optional[list] = field(default=None, repr=False)
    threshold_metrics: Optional[MulticlassThresholdMetrics] = \
        field(default=None, repr=False)
    top_k_metrics: Optional[dict] = field(default=None, repr=False)
    conf_matrix_by_threshold: Optional[dict] = field(default=None, repr=False)
    misclassification: Optional[dict] = field(default=None, repr=False)

    def to_json(self) -> dict:
        """Serialization hook consumed by EvaluatorBase.to_json: nested
        threshold metrics keep the reference's camelCase schema."""
        return {
            "precision": self.precision, "recall": self.recall,
            "f1": self.f1, "error": self.error,
            "top_k_accuracy": list(self.top_k_accuracy),
            "confusion": self.confusion,
            "threshold_metrics": (self.threshold_metrics.to_json()
                                  if self.threshold_metrics else None),
            "top_k_metrics": self.top_k_metrics,
            "conf_matrix_by_threshold": self.conf_matrix_by_threshold,
            "misclassification": self.misclassification,
        }


def _weighted_prf(conf: np.ndarray) -> tuple[float, float, float, float]:
    """(precision, recall, f1, error), support-weighted, from a confusion
    matrix conf[label, pred]. F1 is the harmonic mean of the WEIGHTED
    precision/recall — the reference's own definition
    (OpMultiClassificationEvaluator.scala:155: f1 = 2PR/(P+R) from
    weightedPrecision/weightedRecall), deliberately NOT Spark's
    weightedFMeasure (support-weighted mean of per-class F1s)."""
    n_cls = conf.shape[0]
    support = conf.sum(axis=1)
    pred_count = conf.sum(axis=0)
    diag = np.diag(conf)
    prec_c = np.divide(diag, pred_count, out=np.zeros(n_cls),
                       where=pred_count > 0)
    rec_c = np.divide(diag, support, out=np.zeros(n_cls),
                      where=support > 0)
    wsum = max(support.sum(), 1e-12)
    precision = float((prec_c * support).sum() / wsum)
    recall = float((rec_c * support).sum() / wsum)
    f1 = 0.0 if precision + recall == 0 else \
        2 * precision * recall / (precision + recall)
    error = 1.0 - float(diag.sum() / wsum)
    return precision, recall, f1, error


class OpMultiClassificationEvaluator(EvaluatorBase):
    name = "multiclass classification"
    default_metric = "F1"
    metric_directions = {"Precision": True, "Recall": True, "F1": True,
                         "Error": False}

    def __init__(self, top_ns: tuple = (1, 3),
                 top_ks: tuple = (5, 10, 20, 50, 100),
                 thresholds: Optional[tuple] = None,
                 conf_matrix_num_classes: int = 15,
                 conf_matrix_thresholds: tuple = (0.0, 0.2, 0.4, 0.6, 0.8),
                 conf_matrix_min_support: int = 5,
                 with_confusion: bool = False,
                 with_threshold_metrics: bool = True):
        self.top_ns = tuple(top_ns)
        self.top_ks = tuple(top_ks)
        self.thresholds = tuple(thresholds) if thresholds is not None else \
            tuple(round(i / 100.0, 2) for i in range(101))
        self.conf_matrix_num_classes = conf_matrix_num_classes
        self.conf_matrix_thresholds = tuple(conf_matrix_thresholds)
        self.conf_matrix_min_support = conf_matrix_min_support
        self.with_confusion = with_confusion
        self.with_threshold_metrics = with_threshold_metrics

    # -- threshold metrics ---------------------------------------------------
    def _threshold_metrics(self, prob: np.ndarray, y: np.ndarray
                           ) -> MulticlassThresholdMetrics:
        n, n_cls = prob.shape
        thr = np.asarray(self.thresholds)
        true_score = np.where(y < n_cls, prob[np.arange(n), np.clip(y, 0,
                              n_cls - 1)], 0.0)
        top_score = prob.max(axis=1)
        # first threshold index strictly above the score
        true_cut = np.searchsorted(thr, true_score, side="right")
        max_cut = np.searchsorted(thr, top_score, side="right")
        order = np.argsort(-prob, axis=1, kind="stable")
        nT = thr.size

        def rev_count(cuts, mask):
            """out[j] = #{i in mask : cuts[i] > j} for j in [0, nT)."""
            c = np.bincount(cuts[mask], minlength=nT + 1)
            return (mask.sum() - np.cumsum(c)[:nT]).astype(np.int64)

        correct, incorrect, nopred = {}, {}, {}
        for t in self.top_ns:
            in_topn = (order[:, :t] == y[:, None]).any(axis=1)
            cor = rev_count(true_cut, in_topn)
            # incorrect: topN hits count from true_cut..max_cut; misses from
            # 0..max_cut — i.e. all rows to max_cut minus the correct part
            inc = rev_count(max_cut, np.ones(n, bool)) - cor
            correct[t] = cor
            incorrect[t] = inc
            nopred[t] = np.full(nT, n, np.int64) - cor - inc
        return MulticlassThresholdMetrics(
            top_ns=self.top_ns, thresholds=self.thresholds,
            correct_counts=correct, incorrect_counts=incorrect,
            no_prediction_counts=nopred)

    # -- topK metrics --------------------------------------------------------
    def _topk_metrics(self, y: np.ndarray, yhat: np.ndarray,
                      w: np.ndarray) -> dict:
        labels, counts = np.unique(y, return_counts=True)
        by_freq = labels[np.argsort(-counts, kind="stable")]
        out = {"topKs": list(self.top_ks), "Precision": [], "Recall": [],
               "F1": [], "Error": []}
        n_all = max(int(max(y.max(), yhat.max())) + 1, 1) if y.size else 1
        for k in self.top_ks:
            keep = set(int(v) for v in by_freq[:k])
            # rare true labels -> out-of-set class n_all (never predicted)
            y_k = np.where(np.isin(y, list(keep)), y, n_all)
            conf = np.zeros((n_all + 1, n_all + 1))
            np.add.at(conf, (y_k, yhat), w)
            p, r, f1, e = _weighted_prf(conf)
            out["Precision"].append(p)
            out["Recall"].append(r)
            out["F1"].append(f1)
            out["Error"].append(e)
        return out

    # -- confusion by threshold ---------------------------------------------
    def _conf_matrix_by_threshold(self, y, yhat, prob) -> dict:
        labels, counts = np.unique(y, return_counts=True)
        cm_classes = [int(v) for v in
                      labels[np.argsort(-counts, kind="stable")]
                      [:self.conf_matrix_num_classes]]
        idx = {c: i for i, c in enumerate(cm_classes)}
        sel = np.isin(y, cm_classes) & np.isin(yhat, cm_classes)
        yl = np.asarray([idx[int(v)] for v in y[sel]], np.int64)
        yp = np.asarray([idx[int(v)] for v in yhat[sel]], np.int64)
        conf_score = prob[sel].max(axis=1) if prob.size else \
            np.zeros(sel.sum())
        k = len(cm_classes)
        thr = sorted(self.conf_matrix_thresholds)
        matrices = []
        for t in thr:
            m = np.zeros((k, k), np.int64)
            rows = conf_score >= t
            np.add.at(m, (yl[rows], yp[rows]), 1)
            # reference flattens column-major over (label, prediction)
            matrices.append([int(v) for v in m.T.reshape(-1)])
        return {
            "ConfMatrixNumClasses": self.conf_matrix_num_classes,
            "ConfMatrixClassIndices": cm_classes,
            "ConfMatrixThresholds": list(thr),
            "ConfMatrices": matrices,
        }

    # -- misclassification report -------------------------------------------
    def _misclassification(self, y, yhat) -> dict:
        def per_category(keys, others):
            out = []
            cats, totals = np.unique(keys, return_counts=True)
            for c in cats[np.argsort(-totals, kind="stable")]:
                rows = keys == c
                vals, cnts = np.unique(others[rows], return_counts=True)
                correct = int(cnts[vals == c].sum())
                mis = [(int(v), int(n)) for v, n in zip(vals, cnts) if v != c]
                mis.sort(key=lambda t: -t[1])
                out.append({
                    "Category": float(c),
                    "TotalCount": int(rows.sum()),
                    "CorrectCount": correct,
                    "MisClassifications": [
                        {"ClassIndex": float(v), "Count": n}
                        for v, n in mis[:self.conf_matrix_min_support]],
                })
            return out
        return {
            "ConfMatrixMinSupport": self.conf_matrix_min_support,
            "MisClassificationsByLabel": per_category(y, yhat),
            "MisClassificationsByPrediction": per_category(yhat, y),
        }

    def metric_from_arrays(self, y, pred_col, metric=None, w=None) -> float:
        """Summary-only path for the CV sweep: one confusion matrix, none of
        the threshold/topK/misclassification report families."""
        m = metric or self.default_metric
        y = np.asarray(y).astype(np.int64)
        yhat = np.asarray(pred_col.prediction).astype(np.int64)
        w = np.ones_like(y, dtype=np.float64) if w is None else np.asarray(w)
        n_cls = max(int(y.max()), int(yhat.max())) + 1 if y.size else 1
        conf = np.zeros((n_cls, n_cls))
        np.add.at(conf, (y, yhat), w)
        p, r, f1, e = _weighted_prf(conf)
        return {"Precision": p, "Recall": r, "F1": f1, "Error": e}.get(
            m) if m in ("Precision", "Recall", "F1", "Error") else \
            self.metric_value(self.evaluate_arrays(y, pred_col, w), m)

    def evaluate_arrays(self, y, pred_col, w=None) -> MultiClassificationMetrics:
        y = np.asarray(y).astype(np.int64)
        yhat = np.asarray(pred_col.prediction).astype(np.int64)
        w = np.ones_like(y, dtype=np.float64) if w is None else np.asarray(w)
        prob = np.asarray(pred_col.probability)
        n_cls = max(int(y.max()), int(yhat.max())) + 1 if y.size else 1
        conf = np.zeros((n_cls, n_cls))
        np.add.at(conf, (y, yhat), w)
        precision, recall, f1, error = _weighted_prf(conf)
        wsum = max(w.sum(), 1e-12)
        topks = []
        if prob.size and prob.ndim == 2 and prob.shape[1] > 1:
            order = np.argsort(-prob, axis=1, kind="stable")
            for k in self.top_ns:
                hit = (order[:, :k] == y[:, None]).any(axis=1)
                topks.append(float((hit * w).sum() / wsum))
        thr_m = None
        cm_thr = None
        if self.with_threshold_metrics and prob.size and prob.ndim == 2:
            thr_m = self._threshold_metrics(prob, y)
            cm_thr = self._conf_matrix_by_threshold(y, yhat, prob)
        return MultiClassificationMetrics(
            precision=precision, recall=recall, f1=f1, error=error,
            top_k_accuracy=tuple(topks),
            confusion=conf.tolist() if self.with_confusion else None,
            threshold_metrics=thr_m,
            top_k_metrics=self._topk_metrics(y, yhat, w),
            conf_matrix_by_threshold=cm_thr,
            misclassification=self._misclassification(y, yhat))
