"""Evaluator base.

Parity: reference ``core/.../evaluators/OpEvaluatorBase.scala:113-226`` —
evaluators consume (label, prediction) and emit a typed metrics bundle;
each declares its default metric and whether larger is better (drives the
ModelSelector's argbest).
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["EvaluatorBase"]


class EvaluatorBase:
    name: str = "evaluator"
    default_metric: str = ""
    #: metric name -> larger_is_better
    metric_directions: dict[str, bool] = {}

    def evaluate_arrays(self, y, pred_col, w=None) -> Any:
        """Compute metrics from a label array + PredictionColumn."""
        raise NotImplementedError

    def evaluate(self, data, label_name: str, pred_name: str) -> Any:
        """Evaluate against a PipelineData holding label + prediction cols."""
        y = data.device_col(label_name).values
        pred = data.device_col(pred_name)
        return self.evaluate_arrays(y, pred)

    def metric_value(self, metrics: Any, metric: Optional[str] = None) -> float:
        m = metric or self.default_metric
        return float(getattr(metrics, _snake(m)))

    def larger_is_better(self, metric: Optional[str] = None) -> bool:
        m = metric or self.default_metric
        return self.metric_directions.get(m, True)

    @staticmethod
    def to_json(metrics: Any) -> dict:
        d = asdict(metrics)
        return {k: (v.tolist() if isinstance(v, np.ndarray) else v)
                for k, v in d.items()}


def _snake(name: str) -> str:
    """auPR -> au_pr, AuROC -> au_roc, F1 -> f1, Error -> error."""
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and (not name[i - 1].isupper()):
            out.append("_")
        out.append(ch.lower())
    return "".join(out).replace("__", "_")
