"""Evaluator base.

Parity: reference ``core/.../evaluators/OpEvaluatorBase.scala:113-226`` —
evaluators consume (label, prediction) and emit a typed metrics bundle;
each declares its default metric and whether larger is better (drives the
ModelSelector's argbest).
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["EvaluatorBase"]


class EvaluatorBase:
    name: str = "evaluator"
    default_metric: str = ""
    #: metric name -> larger_is_better
    metric_directions: dict[str, bool] = {}

    def evaluate_arrays(self, y, pred_col, w=None) -> Any:
        """Compute metrics from a label array + PredictionColumn."""
        raise NotImplementedError

    def evaluate(self, data, label_name: str, pred_name: str) -> Any:
        """Evaluate against a PipelineData holding label + prediction cols."""
        y = data.device_col(label_name).values
        pred = data.device_col(pred_name)
        return self.evaluate_arrays(y, pred)

    def metric_value(self, metrics: Any, metric: Optional[str] = None) -> float:
        m = metric or self.default_metric
        return float(getattr(metrics, _snake(m)))

    def larger_is_better(self, metric: Optional[str] = None) -> bool:
        m = metric or self.default_metric
        return self.metric_directions.get(m, True)

    def metric_from_arrays(self, y, pred_col, metric: Optional[str] = None,
                           w=None) -> float:
        """One scalar metric — the CV sweep's hot call. Default computes the
        full bundle; evaluators with expensive report families override with
        a summary-only pass."""
        return self.metric_value(self.evaluate_arrays(y, pred_col, w),
                                 metric)

    @staticmethod
    def to_json(metrics: Any) -> dict:
        def conv(v):
            if isinstance(v, dict):
                return {str(k): conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [conv(x) for x in v]
            if isinstance(v, np.ndarray):
                return conv(v.tolist())
            if isinstance(v, np.integer):
                return int(v)
            if isinstance(v, (float, np.floating)):
                # non-finite floats are not valid strict JSON
                f = float(v)
                return f if np.isfinite(f) else None
            return v
        if hasattr(metrics, "to_json") and callable(metrics.to_json):
            return conv(metrics.to_json())
        return conv(asdict(metrics))


def _snake(name: str) -> str:
    """auPR -> au_pr, AuROC -> au_roc, F1 -> f1, Error -> error."""
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0 and (not name[i - 1].isupper()):
            out.append("_")
        out.append(ch.lower())
    return "".join(out).replace("__", "_")
