"""Evaluator factories incl. arbitrary custom-metric evaluators.

Parity: reference ``core/.../evaluators/Evaluators.scala:44-319`` — the
``Evaluators.BinaryClassification.auROC()`` family of constructors plus
``.custom(metricName, largerBetter, evaluateFn)`` building an evaluator
around an arbitrary user lambda over (label, rawPrediction, probability,
prediction).

TPU-first: the custom ``evaluate_fn`` receives host numpy views
``(y, raw, prob, pred)`` pulled once per evaluation — custom metrics are
host-side by contract (they're user lambdas, not jittable), while the
built-in evaluators stay on-device.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase
from transmogrifai_tpu.evaluators.binary import OpBinaryClassificationEvaluator
from transmogrifai_tpu.evaluators.extras import (
    OpBinScoreEvaluator, OPLogLoss, SingleMetric,
)
from transmogrifai_tpu.evaluators.multi import OpMultiClassificationEvaluator
from transmogrifai_tpu.evaluators.regression import OpRegressionEvaluator

__all__ = ["Evaluators", "CustomEvaluator"]


class CustomEvaluator(EvaluatorBase):
    """Evaluator around a user metric function (reference
    ``Evaluators.*.custom``). ``evaluate_fn(y, raw, prob, pred) -> float``
    over numpy arrays: y [n], raw [n, k], prob [n, k], pred [n]."""

    def __init__(self, metric_name: str, larger_better: bool = True,
                 evaluate_fn: Optional[Callable] = None,
                 name: Optional[str] = None):
        if evaluate_fn is None:
            raise ValueError("CustomEvaluator needs an evaluate_fn")
        self.name = name or metric_name
        self.default_metric = metric_name
        self.metric_directions = {metric_name: bool(larger_better)}
        self.evaluate_fn = evaluate_fn

    def evaluate_arrays(self, y, pred_col, w=None) -> SingleMetric:
        y = np.asarray(y, np.float64)
        raw = np.asarray(pred_col.raw_prediction, np.float64)
        prob = np.asarray(pred_col.probability, np.float64)
        pred = np.asarray(pred_col.prediction, np.float64)
        n = y.shape[0]
        return SingleMetric(self.default_metric,
                            float(self.evaluate_fn(y, raw[:n], prob[:n],
                                                   pred[:n])))

    def metric_value(self, metrics: SingleMetric, metric=None) -> float:
        return float(metrics.value)


def _with_default(evaluator, metric: str):
    evaluator.default_metric = metric
    return evaluator


class Evaluators:
    """Factory namespace (reference ``Evaluators.scala``)."""

    class BinaryClassification:
        @staticmethod
        def apply() -> OpBinaryClassificationEvaluator:
            return Evaluators.BinaryClassification.au_roc()

        @staticmethod
        def au_roc() -> OpBinaryClassificationEvaluator:
            return _with_default(OpBinaryClassificationEvaluator(), "auROC")

        @staticmethod
        def au_pr() -> OpBinaryClassificationEvaluator:
            return _with_default(OpBinaryClassificationEvaluator(), "auPR")

        @staticmethod
        def precision() -> OpBinaryClassificationEvaluator:
            return _with_default(OpBinaryClassificationEvaluator(),
                                 "Precision")

        @staticmethod
        def recall() -> OpBinaryClassificationEvaluator:
            return _with_default(OpBinaryClassificationEvaluator(), "Recall")

        @staticmethod
        def f1() -> OpBinaryClassificationEvaluator:
            return _with_default(OpBinaryClassificationEvaluator(), "F1")

        @staticmethod
        def error() -> OpBinaryClassificationEvaluator:
            return _with_default(OpBinaryClassificationEvaluator(), "Error")

        @staticmethod
        def brier_score() -> OpBinScoreEvaluator:
            return OpBinScoreEvaluator()

        @staticmethod
        def log_loss() -> OPLogLoss:
            return OPLogLoss()

        @staticmethod
        def custom(metric_name: str, larger_better: bool = True,
                   evaluate_fn: Optional[Callable] = None) -> CustomEvaluator:
            return CustomEvaluator(metric_name, larger_better, evaluate_fn)

    class MultiClassification:
        @staticmethod
        def apply() -> OpMultiClassificationEvaluator:
            return Evaluators.MultiClassification.f1()

        @staticmethod
        def precision() -> OpMultiClassificationEvaluator:
            return _with_default(OpMultiClassificationEvaluator(),
                                 "Precision")

        @staticmethod
        def recall() -> OpMultiClassificationEvaluator:
            return _with_default(OpMultiClassificationEvaluator(), "Recall")

        @staticmethod
        def f1() -> OpMultiClassificationEvaluator:
            return _with_default(OpMultiClassificationEvaluator(), "F1")

        @staticmethod
        def error() -> OpMultiClassificationEvaluator:
            return _with_default(OpMultiClassificationEvaluator(), "Error")

        @staticmethod
        def custom(metric_name: str, larger_better: bool = True,
                   evaluate_fn: Optional[Callable] = None) -> CustomEvaluator:
            return CustomEvaluator(metric_name, larger_better, evaluate_fn)

    class Regression:
        @staticmethod
        def apply() -> OpRegressionEvaluator:
            return Evaluators.Regression.rmse()

        @staticmethod
        def rmse() -> OpRegressionEvaluator:
            return _with_default(OpRegressionEvaluator(), "RMSE")

        @staticmethod
        def mse() -> OpRegressionEvaluator:
            return _with_default(OpRegressionEvaluator(), "MSE")

        @staticmethod
        def mae() -> OpRegressionEvaluator:
            return _with_default(OpRegressionEvaluator(), "MAE")

        @staticmethod
        def r2() -> OpRegressionEvaluator:
            return _with_default(OpRegressionEvaluator(), "R2")

        @staticmethod
        def custom(metric_name: str, larger_better: bool = True,
                   evaluate_fn: Optional[Callable] = None) -> CustomEvaluator:
            return CustomEvaluator(metric_name, larger_better, evaluate_fn)
