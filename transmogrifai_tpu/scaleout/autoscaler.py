"""SLO-driven autoscaling over the replica fleet.

The scaling signals are the ones the platform already keeps — nothing
new is measured:

- **scale-up** fires when the router's SLO engine reports a fast-burn
  alert (PR 9's multi-window burn rates over router-observed
  availability/latency: the error budget is burning at page rate, add
  capacity before it pages) OR the mean replica admission-queue fill
  ratio crosses ``queue_high`` (PR 6's backpressure signal, read from
  heartbeats: the fleet is absorbing load into queues).
- **scale-down** fires after ``low_steps`` consecutive evaluations
  under ``queue_low`` with no burn — sustained idleness, not one quiet
  tick.
- **host pressure guards the decisions** (PR 10's
  ``resources.pressure_state()``): a pressured host never scales UP
  (another jax process on an exhausted host makes the incident worse),
  and RSS pressure forces a scale-down step toward ``min_replicas``
  even under load — shedding a replica IS the host's degradation-
  ladder rung at fleet scope (the remaining replicas shed load via
  backpressure, which clients retry; memory exhaustion drops the whole
  host).

Bounded by ``min_replicas``/``max_replicas`` with a ``cooldown_s``
between actions so one noisy window can't flap the fleet. Every signal
is injectable (``burn_fn``/``queue_ratio_fn``/``pressure_fn``) and
``evaluate()`` is a pure decision function — tests drive transitions
deterministically; ``start()`` runs it on a timer against the real
supervisor.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Callable, Optional

from transmogrifai_tpu.utils.events import events

__all__ = ["Autoscaler"]


class Autoscaler:
    def __init__(self, supervisor, *, min_replicas: int = 1,
                 max_replicas: int = 8,
                 queue_high: float = 0.5, queue_low: float = 0.05,
                 low_steps: int = 3,
                 cooldown_s: float = 30.0,
                 interval_s: float = 5.0,
                 burn_fn: Optional[Callable[[], bool]] = None,
                 queue_ratio_fn: Optional[Callable[[], float]] = None,
                 pressure_fn: Optional[Callable[[], dict]] = None):
        self.supervisor = supervisor
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.low_steps = int(low_steps)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self._burn_fn = burn_fn
        self._queue_ratio_fn = queue_ratio_fn
        self._pressure_fn = pressure_fn
        self._low_streak = 0
        self._last_action_at: Optional[float] = None
        self.evaluations = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals (each injectable) -------------------------------------------
    def _burning(self) -> bool:
        if self._burn_fn is not None:
            return bool(self._burn_fn())
        engine = getattr(getattr(self.supervisor, "router", None),
                         "slo_engine", None)
        if engine is None:
            return False
        try:
            return engine.page_firing()
        except Exception as e:  # noqa: BLE001 — a broken signal must not kill scaling
            warnings.warn(f"autoscaler: burn signal failed "
                          f"({type(e).__name__}: {e})", RuntimeWarning)
            return False

    def _queue_ratio(self) -> float:
        if self._queue_ratio_fn is not None:
            return float(self._queue_ratio_fn())
        try:
            return float(self.supervisor.queue_ratio())
        except Exception as e:  # noqa: BLE001 — see _burning
            warnings.warn(f"autoscaler: queue signal failed "
                          f"({type(e).__name__}: {e})", RuntimeWarning)
            return 0.0

    def _pressure(self) -> dict:
        if self._pressure_fn is not None:
            return dict(self._pressure_fn())
        from transmogrifai_tpu.utils.resources import pressure_state
        return pressure_state()

    # -- decision -------------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Optional[dict]:
        """One scaling decision (pure; ``apply`` acts on it). Returns
        ``{"direction", "fromReplicas", "toReplicas", "reason"}`` or
        None."""
        now = time.monotonic() if now is None else now
        self.evaluations += 1
        current = self.supervisor.replica_count()
        in_cooldown = (self._last_action_at is not None
                       and now - self._last_action_at < self.cooldown_s)
        burning = self._burning()
        ratio = self._queue_ratio()
        pressure = self._pressure()
        pressured = bool(pressure.get("rssPressure"))
        want_up = burning or ratio >= self.queue_high
        if want_up:
            self._low_streak = 0
        elif ratio <= self.queue_low and not burning:
            self._low_streak += 1
        else:
            self._low_streak = 0
        if pressured and current > self.min_replicas \
                and not in_cooldown:
            # the fleet-scope degradation rung: shed a replica to
            # relieve the host, even under load (see module docstring)
            return {"direction": "down", "fromReplicas": current,
                    "toReplicas": current - 1,
                    "reason": "host_pressure"}
        if in_cooldown:
            return None
        if want_up and not pressured and current < self.max_replicas:
            return {"direction": "up", "fromReplicas": current,
                    "toReplicas": current + 1,
                    "reason": "slo_burn" if burning else "queue_depth"}
        if self._low_streak >= self.low_steps \
                and current > self.min_replicas:
            return {"direction": "down", "fromReplicas": current,
                    "toReplicas": current - 1, "reason": "idle"}
        return None

    def apply(self, decision: Optional[dict],
              now: Optional[float] = None) -> bool:
        if decision is None:
            return False
        now = time.monotonic() if now is None else now
        self.supervisor.scale_to(decision["toReplicas"])
        self._last_action_at = now
        self._low_streak = 0
        if decision["direction"] == "up":
            self.scale_ups += 1
        else:
            self.scale_downs += 1
        events.emit("scaleout.autoscale", **decision)
        return True

    def step(self, now: Optional[float] = None) -> Optional[dict]:
        decision = self.evaluate(now)
        self.apply(decision, now)
        return decision

    # -- timer ---------------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="transmogrifai-scaleout-autoscaler",
            daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — scaling must not die of one bad tick
                warnings.warn(
                    f"autoscaler: step failed ({type(e).__name__}: "
                    f"{e})", RuntimeWarning)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def to_json(self) -> dict:
        return {"minReplicas": self.min_replicas,
                "maxReplicas": self.max_replicas,
                "queueHigh": self.queue_high,
                "queueLow": self.queue_low,
                "cooldownSeconds": self.cooldown_s,
                "evaluations": self.evaluations,
                "scaleUps": self.scale_ups,
                "scaleDowns": self.scale_downs,
                "lowStreak": self._low_streak}
