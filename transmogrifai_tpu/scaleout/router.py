"""The scale-out front door: a consistent-hash router over replica
workers with bounded spillover, heartbeat markdown, and retry-not-drop
semantics.

Routing policy:

- **consistent hash on model id** (``ConsistentHashRing``, virtual
  nodes): one model's traffic lands on one primary replica, so each
  replica's compiled-program cache holds the programs of the models it
  actually serves — fleet-wide HBM is sharded, not mirrored. Ring
  membership changes move only the affected arc (the consistent-hash
  property a modulo hash lacks), so a respawn doesn't reshuffle every
  model's affinity.
- **bounded spillover**: a primary answering 503 (its admission queue
  is full — the replica's OWN backpressure) spills the request to the
  next ``spill`` distinct replicas in ring order. Spillover is the
  pressure valve that turns single-model hotspots into fleet-wide
  utilization; the bound keeps a poisoned request from touring every
  replica.
- **markdown**: a replica that refuses connections (crashed, killed,
  mid-respawn) is marked down immediately and skipped by routing until
  the supervisor's heartbeat monitor marks it back up. The in-flight
  request that DISCOVERED the death is retried on the next candidate —
  scoring is idempotent, so a replica kill costs retries, never client
  drops.
- every proxied reply carries ``X-Served-By: <replica_id>`` so a load
  harness can prove where traffic actually went.

The router itself is model-free and jax-free: it proxies bytes. A
binary columnar frame (``application/x-tmog-frame``) is routed by
PEEKING the fixed-offset model id in its header (``wireformat.
peek_model_id``) and forwarded as opaque bytes — the router never
decodes a column. Its ``/metrics`` renders ``transmogrifai_router_*``
plus the standard process series; ``/healthz`` reports the replica
table and SLO state (the router's own availability/latency objectives
can drive the autoscaler's scale-up signal). Chaos seam: ``fault_point
("scaleout.route")`` fires per proxy attempt.
"""

from __future__ import annotations

import bisect
import hashlib
import http.client
import json
import threading
import time
from typing import Optional

from transmogrifai_tpu.serving.aiohttp_core import (
    AsyncHTTPServer, Request, Response,
)
from transmogrifai_tpu.serving.metrics import LATENCY_BUCKETS_S
from transmogrifai_tpu.serving.wireformat import (
    CONTENT_TYPE_FRAME, WireFormatError, peek_model_id,
)
from transmogrifai_tpu.utils.events import events

__all__ = ["ConsistentHashRing", "Router", "RouterMetrics",
           "ReplicaDown"]


class ReplicaDown(RuntimeError):
    """Transport-level failure talking to a replica (connect/read)."""


class ConsistentHashRing:
    """Consistent hashing with virtual nodes. ``order(key)`` walks the
    ring from the key's position and returns every DISTINCT member once
    — the primary first, then the spillover successors. Membership
    changes move only the arcs adjacent to the changed member.

    Members carry a **placement weight** (default 1.0): a member gets
    ``round(vnodes x weight)`` virtual nodes, so its expected share of
    the keyspace scales with the weight. This is the skew-rebalancing
    lever — an overloaded replica's weight drops, it sheds arcs (and
    only arcs: keys whose primary didn't change keep their affinity,
    the property a full reshuffle lacks)."""

    def __init__(self, members=(), vnodes: int = 64):
        self.vnodes = int(vnodes)
        #: membership changes swap in a freshly built (ring, hashes)
        #: pair under the lock; order() snapshots the pair once, so a
        #: handler thread mid-walk can never index a ring that a
        #: concurrent rebuild just shrank
        self._lock = threading.Lock()
        self._ring: list[tuple[int, str]] = []
        self._hashes: list[int] = []
        self._members: set[str] = set()
        #: member -> placement weight (only non-default entries kept)
        self._weights: dict[str, float] = {}
        for m in members:
            self.add(m)

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode()).digest()[:8], "big")

    def _member_vnodes(self, member: str) -> int:
        # at least one vnode: a weighted-down member stays routable
        # (markdown, not weighting, is how a member leaves routing)
        return max(1, round(self.vnodes * self._weights.get(member, 1.0)))

    def _rebuild(self) -> None:
        ring = sorted(
            (self._hash(f"{m}#{i}"), m)
            for m in self._members
            for i in range(self._member_vnodes(m)))
        self._ring = ring
        self._hashes = [h for h, _ in ring]

    def add(self, member: str, weight: Optional[float] = None) -> None:
        with self._lock:
            changed = False
            if member not in self._members:
                self._members.add(member)
                changed = True
            if weight is not None \
                    and self._weights.get(member, 1.0) != float(weight):
                self._weights[member] = float(weight)
                changed = True
            if changed:
                self._rebuild()

    def remove(self, member: str) -> None:
        with self._lock:
            if member in self._members:
                self._members.discard(member)
                self._weights.pop(member, None)
                self._rebuild()

    def set_weights(self, weights: dict) -> bool:
        """Apply a full member -> weight map in ONE rebuild (the
        rebalancer's bulk path; per-member ``add`` would rebuild the
        ring N times). Unknown members are ignored. True if the ring
        changed."""
        with self._lock:
            new = {m: float(w) for m, w in weights.items()
                   if m in self._members and float(w) != 1.0}
            for m in self._members:
                if m in weights:
                    continue
                if m in self._weights:
                    new[m] = self._weights[m]
            if new == self._weights:
                return False
            self._weights = new
            self._rebuild()
            return True

    def weights(self) -> dict:
        with self._lock:
            return {m: self._weights.get(m, 1.0)
                    for m in sorted(self._members)}

    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def order(self, key: str) -> list[str]:
        """Every member once, in ring order starting at ``key``'s
        position (primary first)."""
        with self._lock:
            ring, hashes = self._ring, self._hashes
            n_members = len(self._members)
        if not ring:
            return []
        start = bisect.bisect_left(hashes, self._hash(key)) % len(ring)
        seen: list[str] = []
        seen_set: set[str] = set()
        n = len(ring)
        for i in range(n):
            _, m = ring[(start + i) % n]
            if m not in seen_set:
                seen.append(m)
                seen_set.add(m)
                if len(seen_set) == n_members:
                    break
        return seen


class RouterMetrics:
    """Router-side request accounting. Deliberately shaped like the
    slice of ``ServingMetrics`` the SLO engine reads (``completed`` /
    ``failed`` counters + ``latency_histogram()``), so availability and
    latency objectives bind to router-observed traffic unchanged —
    which is what the autoscaler's burn-rate scale-up signal watches."""

    def __init__(self):
        self._lock = threading.Lock()
        self.completed = 0          # 2xx replies proxied back
        self.failed = 0             # 5xx/transport after all candidates
        self.client_errors = 0      # 4xx from the replica (caller bug)
        self.spillovers = 0         # 503 -> next replica
        self.retries = 0            # transport error -> next replica
        self.markdowns = 0          # replicas marked down by the router
        self.no_replica = 0         # no routable replica at all
        self.rebalances = 0         # skew-triggered ring re-weightings
        self.by_replica: dict[str, int] = {}
        self._lat_buckets = [0] * (len(LATENCY_BUCKETS_S) + 1)
        self._lat_sum = 0.0

    def record(self, replica_id: Optional[str], status: int,
               latency_s: float) -> None:
        with self._lock:
            if replica_id is not None:
                self.by_replica[replica_id] = \
                    self.by_replica.get(replica_id, 0) + 1
            if 200 <= status < 300:
                self.completed += 1
            elif 400 <= status < 500:
                self.client_errors += 1
            else:
                self.failed += 1
            self._lat_sum += latency_s
            for i, bound in enumerate(LATENCY_BUCKETS_S):
                if latency_s <= bound:
                    self._lat_buckets[i] += 1
                    break
            else:
                self._lat_buckets[-1] += 1

    def count(self, attr: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)

    def latency_histogram(self) -> dict:
        with self._lock:
            per_bin = list(self._lat_buckets)
            total = self._lat_sum
        buckets: dict = {}
        running = 0
        for bound, n in zip(LATENCY_BUCKETS_S, per_bin):
            running += n
            buckets[f"{bound:g}"] = running
        running += per_bin[-1]
        buckets["+Inf"] = running
        return {"buckets": buckets, "sum": total, "count": running}

    def to_json(self) -> dict:
        with self._lock:
            return {"completed": self.completed, "failed": self.failed,
                    "clientErrors": self.client_errors,
                    "spillovers": self.spillovers,
                    "retries": self.retries,
                    "markdowns": self.markdowns,
                    "noReplica": self.no_replica,
                    "rebalances": self.rebalances,
                    "byReplica": dict(self.by_replica)}


class _Replica:
    __slots__ = ("replica_id", "host", "port", "state", "changed_at")

    def __init__(self, replica_id, host, port):
        self.replica_id = replica_id
        self.host = host
        self.port = int(port)
        self.state = "up"            # up | down | draining
        self.changed_at = time.time()

    def to_json(self) -> dict:
        return {"replicaId": self.replica_id, "host": self.host,
                "port": self.port, "state": self.state,
                "changedAt": self.changed_at}


class Router:
    """HTTP front proxying ``POST /score[/<model_id>]`` across replica
    workers (see module docstring for the policy). The front is the
    shared event-loop core (``serving/aiohttp_core.py``); the sync
    ``dispatch`` runs on its bounded thread pool with one upstream
    keep-alive connection per (pool thread, replica) — the hop costs a
    request/response on a warm socket, not a handshake."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 spill: int = 2, vnodes: int = 64,
                 route_field: str = "model",
                 upstream_timeout_s: float = 30.0,
                 slo=None, load_half_life_s: float = 30.0):
        self.ring = ConsistentHashRing(vnodes=vnodes)
        self.metrics = RouterMetrics()
        #: per-model EWMA request rate observed AT THE ROUTER — the
        #: skew-rebalancing signal (the same decayed-rate estimator the
        #: tenancy prewarm ranking uses)
        from transmogrifai_tpu.tenancy.popularity import (
            PopularityTracker,
        )
        self.load = PopularityTracker(load_half_life_s)
        self.spill = int(spill)
        self.route_field = route_field
        self.upstream_timeout_s = float(upstream_timeout_s)
        self._replicas: dict[str, _Replica] = {}
        self._lock = threading.Lock()
        self._host = host
        self._requested_port = int(port)
        self._http: Optional[AsyncHTTPServer] = None
        self._tls = threading.local()
        #: SLO engine over ROUTER-observed traffic (availability /
        #: latency objectives; the autoscaler's burn signal)
        self.slo_engine = None
        if slo is not None:
            from transmogrifai_tpu.utils.slo import SLOEngine
            self.slo_engine = SLOEngine.for_serving(
                slo, lambda: [self.metrics])
        self._registry_obj = None

    # -- membership (supervisor-driven) --------------------------------------
    def set_replica(self, replica_id: str, port: int,
                    host: str = "127.0.0.1") -> None:
        """Add or re-point a replica (respawns get a fresh port)."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.port != int(port) or rep.host != host:
                self._replicas[replica_id] = _Replica(
                    replica_id, host, port)
            else:
                rep.state = "up"
                rep.changed_at = time.time()
        self.ring.add(replica_id)

    def remove_replica(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)
        self.ring.remove(replica_id)

    def _set_state(self, replica_id: str, state: str) -> bool:
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.state == state:
                return False
            rep.state = state
            rep.changed_at = time.time()
            return True

    def mark_down(self, replica_id: str, reason: str = "") -> None:
        """Take a replica out of routing (crash, stale heartbeat). The
        requests it was serving are retried on its ring successors."""
        if self._set_state(replica_id, "down"):
            self.metrics.count("markdowns")
            events.emit("scaleout.markdown", replica=replica_id,
                        reason=reason or None)

    def mark_up(self, replica_id: str) -> None:
        if self._set_state(replica_id, "up"):
            events.emit("scaleout.markup", replica=replica_id)

    def set_draining(self, replica_id: str) -> None:
        """Stop routing NEW requests to a replica (rolling swap / scale
        down) without counting it as a failure."""
        self._set_state(replica_id, "draining")

    def replicas(self) -> dict:
        with self._lock:
            return {rid: rep.to_json()
                    for rid, rep in self._replicas.items()}

    # -- routing --------------------------------------------------------------
    def candidates(self, model_id: str) -> list[_Replica]:
        """The primary + up to ``spill`` routable successors for one
        model id (ring order, down/draining filtered out)."""
        order = self.ring.order(model_id)
        out: list[_Replica] = []
        with self._lock:
            for rid in order:
                rep = self._replicas.get(rid)
                if rep is not None and rep.state == "up":
                    out.append(rep)
                    if len(out) > self.spill:
                        break
        return out

    def route_order(self, model_id: str) -> list[str]:
        return [r.replica_id for r in self.candidates(model_id)]

    # -- load skew / rebalancing ---------------------------------------------
    def replica_loads(self) -> dict:
        """replica id -> summed EWMA request rate of the models whose
        PRIMARY arc it owns (spillover traffic intentionally excluded:
        placement decides primaries, so primaries are what placement
        must balance)."""
        loads = {rid: 0.0 for rid in self.ring.members()}
        for model_id, rate in self.load.rank():
            order = self.ring.order(model_id)
            if order:
                loads[order[0]] = loads.get(order[0], 0.0) + rate
        return loads

    def load_skew(self) -> float:
        """max/mean primary load over ring members — 1.0 is perfectly
        balanced; Zipf traffic through an unweighted ring typically
        reads 2-4. The supervisor's rebalance trigger."""
        loads = self.replica_loads()
        if not loads:
            return 1.0
        mean = sum(loads.values()) / len(loads)
        if mean <= 0.0:
            return 1.0
        return max(loads.values()) / mean

    def rebalance(self, min_weight: float = 0.25,
                  max_weight: float = 4.0) -> dict:
        """One damped re-weighting step toward balanced primary load:
        each member's weight moves by ``sqrt(mean/load)`` (square-root
        damping keeps successive rebalances from oscillating around
        the target), clamped to ``[min_weight, max_weight]`` so no
        replica ever sheds ALL its arcs or absorbs the whole keyspace.
        Returns the applied weight map (empty when there's no load
        signal yet)."""
        loads = self.replica_loads()
        total = sum(loads.values())
        if not loads or total <= 0.0:
            return {}
        mean = total / len(loads)
        current = self.ring.weights()
        eps = mean * 1e-3
        weights = {}
        for rid, load in loads.items():
            step = (mean / max(load, eps)) ** 0.5
            weights[rid] = min(max(current.get(rid, 1.0) * step,
                                   min_weight), max_weight)
        skew_before = max(loads.values()) / mean
        if self.ring.set_weights(weights):
            self.metrics.count("rebalances")
            events.emit("scaleout.rebalance",
                        skewBefore=round(skew_before, 3),
                        weights={r: round(w, 3)
                                 for r, w in sorted(weights.items())})
        return weights

    def _upstream(self, rep: _Replica) -> http.client.HTTPConnection:
        """Per-(handler thread, replica) keep-alive connection."""
        pool = getattr(self._tls, "pool", None)
        if pool is None:
            pool = self._tls.pool = {}
        key = (rep.host, rep.port)
        conn = pool.get(key)
        if conn is None:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.upstream_timeout_s)
            pool[key] = conn
        return conn

    def _drop_upstream(self, rep: _Replica) -> None:
        pool = getattr(self._tls, "pool", None)
        if pool is not None:
            conn = pool.pop((rep.host, rep.port), None)
            if conn is not None:
                conn.close()

    def _proxy_once(self, rep: _Replica, path: str, body: bytes,
                    headers: dict) -> tuple:
        """One upstream attempt -> (status, reply_headers, payload).
        Transport failures raise :class:`ReplicaDown`. One reconnect is
        attempted first: an idle keep-alive socket the replica closed
        (or a stale pool entry from before a respawn) is not a dead
        replica."""
        from transmogrifai_tpu.utils.faults import fault_point
        fault_point("scaleout.route")
        for attempt in (0, 1):
            conn = self._upstream(rep)
            try:
                conn.request("POST", path, body, headers)
                resp = conn.getresponse()
                payload = resp.read()
                return resp.status, dict(resp.getheaders()), payload
            except Exception as e:  # noqa: BLE001 — classified below
                self._drop_upstream(rep)
                if attempt == 1:
                    raise ReplicaDown(
                        f"replica {rep.replica_id} at {rep.host}:"
                        f"{rep.port}: {type(e).__name__}: {e}") from e

    def dispatch(self, model_id: str, body: bytes,
                 headers: Optional[dict] = None) -> tuple:
        """Route one scoring request: primary, spill on 503, retry next
        on transport death (marking the dead replica down). Returns
        ``(status, headers, payload, replica_id)``; with no routable
        replica or every candidate exhausted, a synthesized 503."""
        headers = dict(headers or {})
        headers.setdefault("Content-Type", "application/json")
        path = f"/score/{model_id}"
        self.load.record(model_id)
        candidates = self.candidates(model_id)
        if not candidates:
            self.metrics.count("no_replica")
            return (503, {"Retry-After": "1.0"},
                    json.dumps({"error": "no routable replica"}).encode(),
                    None)
        last: tuple = (503, {"Retry-After": "0.05"},
                       json.dumps({"error": "all replicas "
                                            "backpressured"}).encode(),
                       None)
        for i, rep in enumerate(candidates):
            try:
                status, rheaders, payload = self._proxy_once(
                    rep, path, body, headers)
            except ReplicaDown as e:
                # the request DISCOVERED the death: mark down, retry on
                # the next candidate — a kill costs retries, not drops
                self.mark_down(rep.replica_id, reason=str(e)[:200])
                self.metrics.count("retries")
                continue
            except Exception as e:  # noqa: BLE001 — injected route faults
                # (chaos site scaleout.route): transient/io failures on
                # the hop retry the next candidate, bounded by the
                # candidate list; harness errors must surface
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                if isinstance(e, FaultHarnessError):
                    raise
                self.metrics.count("retries")
                continue
            if status == 503:
                # the replica's own admission backpressure: spill over
                self.metrics.count("spillovers")
                last = (status, rheaders, payload, rep.replica_id)
                continue
            return status, rheaders, payload, rep.replica_id
        return last

    # -- HTTP front -----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._http.port if self._http else None

    def _registry(self):
        if self._registry_obj is None:
            from transmogrifai_tpu.utils.prometheus import build_registry
            self._registry_obj = build_registry(
                router=self, slo=self.slo_engine, include_app=False)
        return self._registry_obj

    def health(self) -> dict:
        from transmogrifai_tpu.utils.resources import pressure_state
        from transmogrifai_tpu.utils.slo import fold_health
        reps = self.replicas()
        up = sum(1 for r in reps.values() if r["state"] == "up")
        doc = {"status": "ok" if up else "no_replicas",
               "ready": up > 0,
               "replicas": reps,
               "router": self.metrics.to_json(),
               "loadSkew": round(self.load_skew(), 3),
               "ringWeights": {r: round(w, 3)
                               for r, w in self.ring.weights().items()},
               "resources": pressure_state()}
        fold_health(self.slo_engine, doc)
        return doc

    async def _do_get(self, req: Request) -> Response:
        path = req.path
        try:
            if path == "/metrics":
                from transmogrifai_tpu.utils.prometheus import (
                    CONTENT_TYPE,
                )
                body = (await self._http.run_blocking(
                    lambda: self._registry().render())).encode()
                return Response(200, body, CONTENT_TYPE)
            if path == "/healthz":
                doc = await self._http.run_blocking(self.health)
                return Response(200, (json.dumps(doc) + "\n").encode())
            if path == "/replicas":
                return Response(200, (json.dumps(self.replicas())
                                      + "\n").encode())
            return Response.error(404, "only /metrics, /healthz, "
                                       "/replicas, POST /score")
        except Exception as e:  # noqa: BLE001 — a probe must see the failure
            return Response.error(500, f"{type(e).__name__}: "
                                       f"{str(e)[:200]}")

    async def _do_post(self, req: Request) -> Response:
        t0 = time.monotonic()
        path = req.path
        if not (path == "/score" or path.startswith("/score/")):
            return Response.error(404, "POST /score[/<model>]")
        body = req.body or b"{}"
        ctype = (req.header("content-type") or "").split(";")[0].strip()
        is_frame = ctype == CONTENT_TYPE_FRAME
        model_id = path[len("/score/"):] \
            if path.startswith("/score/") else ""
        if not model_id:
            if is_frame:
                # routing key from the frame's FIXED-OFFSET header — the
                # columns stay opaque bytes all the way to the replica
                try:
                    model_id = peek_model_id(body)
                except WireFormatError as e:
                    return Response(400, (json.dumps(
                        {"error": str(e)[:300]}) + "\n").encode())
            else:
                # routing key from the body's route field (popped by
                # the replica fleet anyway)
                try:
                    doc = json.loads(body or b"{}")
                    model_id = str(doc.get(self.route_field, ""))
                except ValueError:
                    model_id = ""
            if not model_id:
                return Response(400, (json.dumps(
                    {"error": "no model id (path or "
                              f"{self.route_field!r} field)"}
                ).encode()))
        fwd = {"Content-Type":
               CONTENT_TYPE_FRAME if is_frame else "application/json"}
        trace = req.header("x-trace-id")
        if trace:
            fwd["X-Trace-Id"] = trace
        status, rheaders, payload, rid = \
            await self._http.run_blocking(
                self.dispatch, model_id, body, fwd)
        self.metrics.record(rid, status, time.monotonic() - t0)
        extra = {k: v for k, v in rheaders.items()
                 if k.lower() in ("x-trace-id", "retry-after")}
        if rid is not None:
            extra["X-Served-By"] = rid
        rtype = next((v for k, v in rheaders.items()
                      if k.lower() == "content-type"),
                     "application/json")
        return Response(status, payload, rtype, extra)

    async def _handle(self, req: Request) -> Response:
        if req.method == "GET":
            return await self._do_get(req)
        if req.method == "POST":
            return await self._do_post(req)
        return Response.error(404, f"method {req.method} unsupported")

    def start(self) -> "Router":
        if self._http is not None:
            return self
        from transmogrifai_tpu.serving.http import MAX_BODY_BYTES
        self._http = AsyncHTTPServer(
            self._handle, port=self._requested_port, host=self._host,
            max_body_bytes=MAX_BODY_BYTES,
            name="transmogrifai-scaleout-router").start()
        return self

    def stop(self) -> None:
        if self._http is None:
            return
        self._http.stop()
        self._http = None
