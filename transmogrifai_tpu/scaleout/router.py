"""The scale-out front door: a consistent-hash router over replica
workers with bounded spillover, heartbeat markdown, and retry-not-drop
semantics.

Routing policy:

- **consistent hash on model id** (``ConsistentHashRing``, virtual
  nodes): one model's traffic lands on one primary replica, so each
  replica's compiled-program cache holds the programs of the models it
  actually serves — fleet-wide HBM is sharded, not mirrored. Ring
  membership changes move only the affected arc (the consistent-hash
  property a modulo hash lacks), so a respawn doesn't reshuffle every
  model's affinity.
- **bounded spillover**: a primary answering 503 (its admission queue
  is full — the replica's OWN backpressure) spills the request to the
  next ``spill`` distinct replicas in ring order. Spillover is the
  pressure valve that turns single-model hotspots into fleet-wide
  utilization; the bound keeps a poisoned request from touring every
  replica.
- **markdown**: a replica that refuses connections (crashed, killed,
  mid-respawn) is marked down immediately and skipped by routing until
  the supervisor's heartbeat monitor marks it back up. The in-flight
  request that DISCOVERED the death is retried on the next candidate —
  scoring is idempotent, so a replica kill costs retries, never client
  drops.
- **safe retries** (the network failure domain): transport failures
  are CLASSIFIED, not lumped. Connect-refused (:class:`ReplicaRefused`)
  means no byte reached the replica — spill to the next candidate
  immediately and mark the refuser down. A mid-request reset
  (:class:`ReplicaDown`) means the request MAY already have been
  scored with the reply lost on the wire — it is retried (same replica
  first, then successors) only because every proxied request carries an
  ``X-Request-Id`` idempotency key minted at the front door (or taken
  from the client's header / frame-meta ``request_id``): replicas keep
  a dedupe ring (``aiohttp_core.DedupeRing``) so the retry is answered
  from cache instead of scored twice. Retries draw from a per-request
  budget (``retry_budget``) with jittered exponential backoff.
- **Retry-After honored**: a 503-answering replica that names its own
  backoff (``Retry-After``) is not re-offered traffic until that many
  seconds pass — the replica's admission controller, not a fixed
  markdown TTL, decides when it wants traffic back.
- **optional hedging**: with ``hedge=True`` a request still unanswered
  at the primary's observed p99 is duplicated to the ring successor
  (same idempotency key); first reply wins. Tail latency is traded for
  bounded duplicate work — never duplicate SCORES, the key dedupes.
- every proxied reply carries ``X-Served-By: <replica_id>`` so a load
  harness can prove where traffic actually went.

The router itself is model-free and jax-free: it proxies bytes. A
binary columnar frame (``application/x-tmog-frame``) is routed by
PEEKING the fixed-offset model id in its header (``wireformat.
peek_model_id``) and forwarded as opaque bytes — the router never
decodes a column. Its ``/metrics`` renders ``transmogrifai_router_*``
plus the standard process series; ``/healthz`` reports the replica
table and SLO state (the router's own availability/latency objectives
can drive the autoscaler's scale-up signal). Chaos seam: ``fault_point
("scaleout.route")`` fires per proxy attempt.
"""

from __future__ import annotations

import bisect
import concurrent.futures
import hashlib
import http.client
import json
import random
import threading
import time
import uuid
from collections import deque
from typing import Optional

from transmogrifai_tpu.serving.aiohttp_core import (
    AsyncHTTPServer, Request, Response, net_counters,
)
from transmogrifai_tpu.serving.metrics import LATENCY_BUCKETS_S
from transmogrifai_tpu.serving.wireformat import (
    CONTENT_TYPE_FRAME, WireFormatError, peek_model_id,
    peek_request_id,
)
from transmogrifai_tpu.utils.events import events

__all__ = ["ConsistentHashRing", "Router", "RouterMetrics",
           "ReplicaDown", "ReplicaRefused"]

#: an upstream's Retry-After is honored up to this long — a replica
#: asking for more is treated as asking for this much (a typo'd header
#: must not silently park a replica for an hour)
RETRY_AFTER_CAP_S = 5.0


class ReplicaDown(RuntimeError):
    """Mid-request transport failure talking to a replica (reset,
    timeout, truncated reply): the request MAY have been delivered —
    and scored — with the reply lost. Retrying is only safe under an
    idempotency key."""


class ReplicaRefused(ReplicaDown):
    """Connect refused: no byte reached the replica, so the request was
    provably NOT scored there. Always safe to retry on the next
    candidate, and grounds for immediate markdown."""


def _is_refused(e: BaseException) -> bool:
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        if isinstance(cur, ConnectionRefusedError):
            return True
        seen.add(id(cur))
        cur = cur.__cause__ or cur.__context__
    return False


class ConsistentHashRing:
    """Consistent hashing with virtual nodes. ``order(key)`` walks the
    ring from the key's position and returns every DISTINCT member once
    — the primary first, then the spillover successors. Membership
    changes move only the arcs adjacent to the changed member.

    Members carry a **placement weight** (default 1.0): a member gets
    ``round(vnodes x weight)`` virtual nodes, so its expected share of
    the keyspace scales with the weight. This is the skew-rebalancing
    lever — an overloaded replica's weight drops, it sheds arcs (and
    only arcs: keys whose primary didn't change keep their affinity,
    the property a full reshuffle lacks)."""

    def __init__(self, members=(), vnodes: int = 64):
        self.vnodes = int(vnodes)
        #: membership changes swap in a freshly built (ring, hashes)
        #: pair under the lock; order() snapshots the pair once, so a
        #: handler thread mid-walk can never index a ring that a
        #: concurrent rebuild just shrank
        self._lock = threading.Lock()
        self._ring: list[tuple[int, str]] = []
        self._hashes: list[int] = []
        self._members: set[str] = set()
        #: member -> placement weight (only non-default entries kept)
        self._weights: dict[str, float] = {}
        for m in members:
            self.add(m)

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode()).digest()[:8], "big")

    def _member_vnodes(self, member: str) -> int:
        # at least one vnode: a weighted-down member stays routable
        # (markdown, not weighting, is how a member leaves routing)
        return max(1, round(self.vnodes * self._weights.get(member, 1.0)))

    def _rebuild(self) -> None:
        ring = sorted(
            (self._hash(f"{m}#{i}"), m)
            for m in self._members
            for i in range(self._member_vnodes(m)))
        self._ring = ring
        self._hashes = [h for h, _ in ring]

    def add(self, member: str, weight: Optional[float] = None) -> None:
        with self._lock:
            changed = False
            if member not in self._members:
                self._members.add(member)
                changed = True
            if weight is not None \
                    and self._weights.get(member, 1.0) != float(weight):
                self._weights[member] = float(weight)
                changed = True
            if changed:
                self._rebuild()

    def remove(self, member: str) -> None:
        with self._lock:
            if member in self._members:
                self._members.discard(member)
                self._weights.pop(member, None)
                self._rebuild()

    def set_weights(self, weights: dict) -> bool:
        """Apply a full member -> weight map in ONE rebuild (the
        rebalancer's bulk path; per-member ``add`` would rebuild the
        ring N times). Unknown members are ignored. True if the ring
        changed."""
        with self._lock:
            new = {m: float(w) for m, w in weights.items()
                   if m in self._members and float(w) != 1.0}
            for m in self._members:
                if m in weights:
                    continue
                if m in self._weights:
                    new[m] = self._weights[m]
            if new == self._weights:
                return False
            self._weights = new
            self._rebuild()
            return True

    def weights(self) -> dict:
        with self._lock:
            return {m: self._weights.get(m, 1.0)
                    for m in sorted(self._members)}

    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def order(self, key: str) -> list[str]:
        """Every member once, in ring order starting at ``key``'s
        position (primary first)."""
        with self._lock:
            ring, hashes = self._ring, self._hashes
            n_members = len(self._members)
        if not ring:
            return []
        start = bisect.bisect_left(hashes, self._hash(key)) % len(ring)
        seen: list[str] = []
        seen_set: set[str] = set()
        n = len(ring)
        for i in range(n):
            _, m = ring[(start + i) % n]
            if m not in seen_set:
                seen.append(m)
                seen_set.add(m)
                if len(seen_set) == n_members:
                    break
        return seen


class RouterMetrics:
    """Router-side request accounting. Deliberately shaped like the
    slice of ``ServingMetrics`` the SLO engine reads (``completed`` /
    ``failed`` counters + ``latency_histogram()``), so availability and
    latency objectives bind to router-observed traffic unchanged —
    which is what the autoscaler's burn-rate scale-up signal watches."""

    def __init__(self):
        self._lock = threading.Lock()
        self.completed = 0          # 2xx replies proxied back
        self.failed = 0             # 5xx/transport after all candidates
        self.client_errors = 0      # 4xx from the replica (caller bug)
        self.spillovers = 0         # 503 -> next replica
        self.retries = 0            # transport error -> retry
        self.refusals = 0           # connect-refused -> immediate spill
        self.resets = 0             # mid-request reset -> keyed retry
        self.hedges = 0             # p99-gated duplicate to successor
        self.markdowns = 0          # replicas marked down by the router
        self.no_replica = 0         # no routable replica at all
        self.rebalances = 0         # skew-triggered ring re-weightings
        self.by_replica: dict[str, int] = {}
        self._lat_buckets = [0] * (len(LATENCY_BUCKETS_S) + 1)
        self._lat_sum = 0.0

    def record(self, replica_id: Optional[str], status: int,
               latency_s: float) -> None:
        with self._lock:
            if replica_id is not None:
                self.by_replica[replica_id] = \
                    self.by_replica.get(replica_id, 0) + 1
            if 200 <= status < 300:
                self.completed += 1
            elif 400 <= status < 500:
                self.client_errors += 1
            else:
                self.failed += 1
            self._lat_sum += latency_s
            for i, bound in enumerate(LATENCY_BUCKETS_S):
                if latency_s <= bound:
                    self._lat_buckets[i] += 1
                    break
            else:
                self._lat_buckets[-1] += 1

    def count(self, attr: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)

    def latency_histogram(self) -> dict:
        with self._lock:
            per_bin = list(self._lat_buckets)
            total = self._lat_sum
        buckets: dict = {}
        running = 0
        for bound, n in zip(LATENCY_BUCKETS_S, per_bin):
            running += n
            buckets[f"{bound:g}"] = running
        running += per_bin[-1]
        buckets["+Inf"] = running
        return {"buckets": buckets, "sum": total, "count": running}

    def to_json(self) -> dict:
        with self._lock:
            return {"completed": self.completed, "failed": self.failed,
                    "clientErrors": self.client_errors,
                    "spillovers": self.spillovers,
                    "retries": self.retries,
                    "refusals": self.refusals,
                    "resets": self.resets,
                    "hedges": self.hedges,
                    "markdowns": self.markdowns,
                    "noReplica": self.no_replica,
                    "rebalances": self.rebalances,
                    "byReplica": dict(self.by_replica)}


class _Replica:
    __slots__ = ("replica_id", "host", "port", "state", "changed_at",
                 "not_before")

    def __init__(self, replica_id, host, port):
        self.replica_id = replica_id
        self.host = host
        self.port = int(port)
        self.state = "up"            # up | down | draining
        self.changed_at = time.time()
        #: monotonic instant before which this replica is not offered
        #: traffic (its own 503 Retry-After ask — see module docstring)
        self.not_before = 0.0

    def to_json(self) -> dict:
        doc = {"replicaId": self.replica_id, "host": self.host,
               "port": self.port, "state": self.state,
               "changedAt": self.changed_at}
        defer = self.not_before - time.monotonic()
        if defer > 0:
            doc["deferredS"] = round(defer, 3)
        return doc


class Router:
    """HTTP front proxying ``POST /score[/<model_id>]`` across replica
    workers (see module docstring for the policy). The front is the
    shared event-loop core (``serving/aiohttp_core.py``); the sync
    ``dispatch`` runs on its bounded thread pool with one upstream
    keep-alive connection per (pool thread, replica) — the hop costs a
    request/response on a warm socket, not a handshake."""

    def __init__(self, *, port: int = 0, host: str = "127.0.0.1",
                 spill: int = 2, vnodes: int = 64,
                 route_field: str = "model",
                 upstream_timeout_s: float = 30.0,
                 slo=None, load_half_life_s: float = 30.0,
                 retry_budget: int = 3,
                 retry_backoff_s: float = 0.01,
                 hedge: bool = False,
                 hedge_min_s: float = 0.02,
                 hedge_max_s: float = 1.0,
                 hedge_min_samples: int = 20):
        self.ring = ConsistentHashRing(vnodes=vnodes)
        self.metrics = RouterMetrics()
        #: transport-failure retries one request may spend, total,
        #: across all candidates (the poisoned-path tour bound)
        self.retry_budget = int(retry_budget)
        self.retry_backoff_s = float(retry_backoff_s)
        self.hedge = bool(hedge)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_max_s = float(hedge_max_s)
        self.hedge_min_samples = int(hedge_min_samples)
        #: per-replica recent proxy latencies (the hedge gate's p99)
        self._lat_lock = threading.Lock()
        self._lat: dict[str, deque] = {}
        self._hedge_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        if self.hedge:
            self._hedge_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=16,
                thread_name_prefix="transmogrifai-hedge")
        #: jittered-backoff RNG (timing only — never correctness)
        self._backoff_rng = random.Random()
        #: per-model EWMA request rate observed AT THE ROUTER — the
        #: skew-rebalancing signal (the same decayed-rate estimator the
        #: tenancy prewarm ranking uses)
        from transmogrifai_tpu.tenancy.popularity import (
            PopularityTracker,
        )
        self.load = PopularityTracker(load_half_life_s)
        self.spill = int(spill)
        self.route_field = route_field
        self.upstream_timeout_s = float(upstream_timeout_s)
        self._replicas: dict[str, _Replica] = {}
        self._lock = threading.Lock()
        self._host = host
        self._requested_port = int(port)
        self._http: Optional[AsyncHTTPServer] = None
        self._tls = threading.local()
        #: SLO engine over ROUTER-observed traffic (availability /
        #: latency objectives; the autoscaler's burn signal)
        self.slo_engine = None
        if slo is not None:
            from transmogrifai_tpu.utils.slo import SLOEngine
            self.slo_engine = SLOEngine.for_serving(
                slo, lambda: [self.metrics])
        self._registry_obj = None

    # -- membership (supervisor-driven) --------------------------------------
    def set_replica(self, replica_id: str, port: int,
                    host: str = "127.0.0.1") -> None:
        """Add or re-point a replica (respawns get a fresh port)."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.port != int(port) or rep.host != host:
                self._replicas[replica_id] = _Replica(
                    replica_id, host, port)
            else:
                rep.state = "up"
                rep.changed_at = time.time()
        self.ring.add(replica_id)

    def remove_replica(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)
        self.ring.remove(replica_id)

    def _set_state(self, replica_id: str, state: str) -> bool:
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.state == state:
                return False
            rep.state = state
            rep.changed_at = time.time()
            return True

    def mark_down(self, replica_id: str, reason: str = "") -> None:
        """Take a replica out of routing (crash, stale heartbeat). The
        requests it was serving are retried on its ring successors."""
        if self._set_state(replica_id, "down"):
            self.metrics.count("markdowns")
            events.emit("scaleout.markdown", replica=replica_id,
                        reason=reason or None)

    def mark_up(self, replica_id: str) -> None:
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is not None:
                rep.not_before = 0.0
        if self._set_state(replica_id, "up"):
            events.emit("scaleout.markup", replica=replica_id)

    def set_draining(self, replica_id: str) -> None:
        """Stop routing NEW requests to a replica (rolling swap / scale
        down) without counting it as a failure."""
        self._set_state(replica_id, "draining")

    def replicas(self) -> dict:
        with self._lock:
            return {rid: rep.to_json()
                    for rid, rep in self._replicas.items()}

    # -- routing --------------------------------------------------------------
    def candidates(self, model_id: str) -> list[_Replica]:
        """The primary + up to ``spill`` routable successors for one
        model id (ring order, down/draining filtered out). Replicas
        inside their self-declared ``Retry-After`` window are deferred
        to the END of the list rather than dropped: honoring the ask
        must never manufacture a no-replica 503."""
        order = self.ring.order(model_id)
        out: list[_Replica] = []
        deferred: list[_Replica] = []
        now = time.monotonic()
        with self._lock:
            for rid in order:
                rep = self._replicas.get(rid)
                if rep is None or rep.state != "up":
                    continue
                if rep.not_before > now:
                    deferred.append(rep)
                else:
                    out.append(rep)
                if len(out) > self.spill:
                    break
        for rep in deferred:
            if len(out) > self.spill:
                break
            out.append(rep)
        return out

    def route_order(self, model_id: str) -> list[str]:
        return [r.replica_id for r in self.candidates(model_id)]

    # -- load skew / rebalancing ---------------------------------------------
    def replica_loads(self) -> dict:
        """replica id -> summed EWMA request rate of the models whose
        PRIMARY arc it owns (spillover traffic intentionally excluded:
        placement decides primaries, so primaries are what placement
        must balance)."""
        loads = {rid: 0.0 for rid in self.ring.members()}
        for model_id, rate in self.load.rank():
            order = self.ring.order(model_id)
            if order:
                loads[order[0]] = loads.get(order[0], 0.0) + rate
        return loads

    def load_skew(self) -> float:
        """max/mean primary load over ring members — 1.0 is perfectly
        balanced; Zipf traffic through an unweighted ring typically
        reads 2-4. The supervisor's rebalance trigger."""
        loads = self.replica_loads()
        if not loads:
            return 1.0
        mean = sum(loads.values()) / len(loads)
        if mean <= 0.0:
            return 1.0
        return max(loads.values()) / mean

    def rebalance(self, min_weight: float = 0.25,
                  max_weight: float = 4.0) -> dict:
        """One damped re-weighting step toward balanced primary load:
        each member's weight moves by ``sqrt(mean/load)`` (square-root
        damping keeps successive rebalances from oscillating around
        the target), clamped to ``[min_weight, max_weight]`` so no
        replica ever sheds ALL its arcs or absorbs the whole keyspace.
        Returns the applied weight map (empty when there's no load
        signal yet)."""
        loads = self.replica_loads()
        total = sum(loads.values())
        if not loads or total <= 0.0:
            return {}
        mean = total / len(loads)
        current = self.ring.weights()
        eps = mean * 1e-3
        weights = {}
        for rid, load in loads.items():
            step = (mean / max(load, eps)) ** 0.5
            weights[rid] = min(max(current.get(rid, 1.0) * step,
                                   min_weight), max_weight)
        skew_before = max(loads.values()) / mean
        if self.ring.set_weights(weights):
            self.metrics.count("rebalances")
            events.emit("scaleout.rebalance",
                        skewBefore=round(skew_before, 3),
                        weights={r: round(w, 3)
                                 for r, w in sorted(weights.items())})
        return weights

    def _upstream(self, rep: _Replica) -> http.client.HTTPConnection:
        """Per-(handler thread, replica) keep-alive connection."""
        pool = getattr(self._tls, "pool", None)
        if pool is None:
            pool = self._tls.pool = {}
        key = (rep.host, rep.port)
        conn = pool.get(key)
        if conn is None:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.upstream_timeout_s)
            pool[key] = conn
        return conn

    def _drop_upstream(self, rep: _Replica) -> None:
        pool = getattr(self._tls, "pool", None)
        if pool is not None:
            conn = pool.pop((rep.host, rep.port), None)
            if conn is not None:
                conn.close()

    def _proxy_once(self, rep: _Replica, path: str, body: bytes,
                    headers: dict) -> tuple:
        """One upstream attempt -> (status, reply_headers, payload).
        Transport failures are CLASSIFIED: :class:`ReplicaRefused` when
        the connect itself was refused (no byte delivered — always safe
        to retry elsewhere), :class:`ReplicaDown` for every mid-request
        failure (the request may have been scored). One silent
        reconnect is attempted only when the failing socket was a
        previously-connected pool entry: an idle keep-alive socket the
        replica closed (or a stale entry from before a respawn) is not
        a dead replica — and nothing was delivered on it, so the
        reconnect can't double-deliver."""
        from transmogrifai_tpu.utils.faults import fault_point
        fault_point("scaleout.route")
        for attempt in (0, 1):
            conn = self._upstream(rep)
            fresh = conn.sock is None
            t0 = time.monotonic()
            try:
                conn.request("POST", path, body, headers)
                resp = conn.getresponse()
                payload = resp.read()
                self._note_latency(rep.replica_id,
                                   time.monotonic() - t0)
                return resp.status, dict(resp.getheaders()), payload
            except Exception as e:  # noqa: BLE001 — classified below
                self._drop_upstream(rep)
                where = (f"replica {rep.replica_id} at {rep.host}:"
                         f"{rep.port}: {type(e).__name__}: {e}")
                if _is_refused(e):
                    raise ReplicaRefused(where) from e
                if fresh or attempt == 1:
                    raise ReplicaDown(where) from e

    # -- hedge gate -----------------------------------------------------------
    def _note_latency(self, replica_id: str, latency_s: float) -> None:
        with self._lat_lock:
            dq = self._lat.get(replica_id)
            if dq is None:
                dq = self._lat[replica_id] = deque(maxlen=512)
            dq.append(latency_s)

    def replica_p99(self, replica_id: str) -> Optional[float]:
        """The replica's observed p99 proxy latency, or None until
        ``hedge_min_samples`` observations exist (hedging on a cold
        estimate would hedge every request)."""
        with self._lat_lock:
            dq = self._lat.get(replica_id)
            if dq is None or len(dq) < self.hedge_min_samples:
                return None
            lat = sorted(dq)
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def _attempt(self, rep: _Replica, successor: Optional[_Replica],
                 path: str, body: bytes, headers: dict) -> tuple:
        """One routed attempt, hedged to ``successor`` when enabled and
        the primary overshoots its own observed p99. Both legs carry
        the same ``X-Request-Id``, so the duplicate is deduped at the
        replica — a hedge can duplicate WORK (bounded, side-effect
        free) but never a client-visible score. Returns ``(status,
        reply_headers, payload, serving_replica)``."""
        if self._hedge_pool is None or successor is None:
            return (*self._proxy_once(rep, path, body, headers), rep)
        p99 = self.replica_p99(rep.replica_id)
        if p99 is None:
            return (*self._proxy_once(rep, path, body, headers), rep)
        delay = min(max(p99, self.hedge_min_s), self.hedge_max_s)
        primary = self._hedge_pool.submit(
            self._proxy_once, rep, path, body, headers)
        try:
            return (*primary.result(timeout=delay), rep)
        except concurrent.futures.TimeoutError:
            pass  # still in flight: hedge fires below
        except ReplicaDown:
            raise  # fast primary failure: no hedge, dispatch classifies
        self.metrics.count("hedges")
        net_counters.hedges += 1
        events.emit("router.hedge", replica=rep.replica_id,
                    successor=successor.replica_id,
                    p99Ms=round(p99 * 1e3, 3))
        hedge = self._hedge_pool.submit(
            self._proxy_once, successor, path, body, headers)
        owner = {primary: rep, hedge: successor}
        pending = set(owner)
        first_error: Optional[BaseException] = None
        while pending:
            done, _ = concurrent.futures.wait(
                pending, timeout=self.upstream_timeout_s,
                return_when=concurrent.futures.FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                pending.discard(fut)
                err = fut.exception()
                if err is None:
                    return (*fut.result(), owner[fut])
                if first_error is None or fut is primary:
                    first_error = err
        raise first_error if first_error is not None else ReplicaDown(
            f"replica {rep.replica_id}: hedged attempts timed out")

    def _note_retry_after(self, rep: _Replica, rheaders: dict) -> None:
        """Honor the replica's own 503 Retry-After ask (bounded) before
        re-offering it traffic."""
        ra = next((v for k, v in rheaders.items()
                   if k.lower() == "retry-after"), None)
        if not ra:
            return
        try:
            defer = min(float(ra), RETRY_AFTER_CAP_S)
        except ValueError:
            return
        if defer > 0:
            rep.not_before = max(rep.not_before,
                                 time.monotonic() + defer)

    def dispatch(self, model_id: str, body: bytes,
                 headers: Optional[dict] = None) -> tuple:
        """Route one scoring request: primary, spill on 503 (honoring
        Retry-After), classified transport retries under a per-request
        budget with jittered backoff (see module docstring). Returns
        ``(status, headers, payload, replica_id)``; with no routable
        replica or every candidate exhausted, a synthesized 503."""
        headers = dict(headers or {})
        headers.setdefault("Content-Type", "application/json")
        if not headers.get("X-Request-Id"):
            # the idempotency key that makes mid-request retries safe;
            # minted here so every upstream hop carries one
            headers["X-Request-Id"] = uuid.uuid4().hex[:16]
        path = f"/score/{model_id}"
        self.load.record(model_id)
        candidates = self.candidates(model_id)
        if not candidates:
            self.metrics.count("no_replica")
            return (503, {"Retry-After": "1.0"},
                    json.dumps({"error": "no routable replica"}).encode(),
                    None)
        last: tuple = (503, {"Retry-After": "0.05"},
                       json.dumps({"error": "all replicas "
                                            "backpressured"}).encode(),
                       None)
        budget = self.retry_budget

        def backoff() -> None:
            spent = self.retry_budget - budget
            base = self.retry_backoff_s * (2 ** max(0, spent - 1))
            time.sleep(base * self._backoff_rng.uniform(0.5, 1.5))

        for i, rep in enumerate(candidates):
            successor = candidates[i + 1] if i + 1 < len(candidates) \
                else None
            same_replica_retries = 1
            while True:
                try:
                    status, rheaders, payload, served = self._attempt(
                        rep, successor, path, body, headers)
                except ReplicaRefused as e:
                    # no byte was delivered: safe immediate spillover,
                    # and the refuser leaves routing until marked up
                    self.mark_down(rep.replica_id, reason=str(e)[:200])
                    self.metrics.count("retries")
                    self.metrics.count("refusals")
                    net_counters.refusals_spilled += 1
                    break  # next candidate
                except ReplicaDown as e:
                    # mid-request failure: the request may have been
                    # scored. The X-Request-Id key makes the retry safe
                    # (replica dedupe ring); try the SAME replica once
                    # first — a connection-level fault is not a dead
                    # replica — then mark down and move on.
                    self.metrics.count("retries")
                    self.metrics.count("resets")
                    net_counters.resets_retried += 1
                    if budget <= 0:
                        self.mark_down(rep.replica_id,
                                       reason=str(e)[:200])
                        return last
                    budget -= 1
                    backoff()
                    if same_replica_retries > 0:
                        same_replica_retries -= 1
                        continue
                    self.mark_down(rep.replica_id, reason=str(e)[:200])
                    break  # next candidate
                except Exception as e:  # noqa: BLE001 — injected route faults
                    # (chaos site scaleout.route): transient/io failures
                    # on the hop retry the next candidate, bounded by
                    # the candidate list; harness errors must surface
                    from transmogrifai_tpu.utils.faults import (
                        FaultHarnessError,
                    )
                    if isinstance(e, FaultHarnessError):
                        raise
                    self.metrics.count("retries")
                    if budget <= 0:
                        return last
                    budget -= 1
                    break  # next candidate
                if status == 503:
                    # the replica's own admission backpressure: spill
                    # over, and honor its Retry-After before offering
                    # it traffic again
                    self.metrics.count("spillovers")
                    self._note_retry_after(served, rheaders)
                    last = (status, rheaders, payload,
                            served.replica_id)
                    break  # next candidate
                return status, rheaders, payload, served.replica_id
        return last

    # -- HTTP front -----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._http.port if self._http else None

    def _registry(self):
        if self._registry_obj is None:
            from transmogrifai_tpu.utils.prometheus import build_registry
            self._registry_obj = build_registry(
                router=self, slo=self.slo_engine, include_app=False)
        return self._registry_obj

    def health(self) -> dict:
        from transmogrifai_tpu.utils.resources import pressure_state
        from transmogrifai_tpu.utils.slo import fold_health
        reps = self.replicas()
        up = sum(1 for r in reps.values() if r["state"] == "up")
        doc = {"status": "ok" if up else "no_replicas",
               "ready": up > 0,
               "replicas": reps,
               "router": self.metrics.to_json(),
               "loadSkew": round(self.load_skew(), 3),
               "ringWeights": {r: round(w, 3)
                               for r, w in self.ring.weights().items()},
               "resources": pressure_state()}
        fold_health(self.slo_engine, doc)
        return doc

    async def _do_get(self, req: Request) -> Response:
        path = req.path
        try:
            if path == "/metrics":
                from transmogrifai_tpu.utils.prometheus import (
                    CONTENT_TYPE,
                )
                body = (await self._http.run_blocking(
                    lambda: self._registry().render())).encode()
                return Response(200, body, CONTENT_TYPE)
            if path == "/healthz":
                doc = await self._http.run_blocking(self.health)
                return Response(200, (json.dumps(doc) + "\n").encode())
            if path == "/replicas":
                return Response(200, (json.dumps(self.replicas())
                                      + "\n").encode())
            return Response.error(404, "only /metrics, /healthz, "
                                       "/replicas, POST /score")
        except Exception as e:  # noqa: BLE001 — a probe must see the failure
            return Response.error(500, f"{type(e).__name__}: "
                                       f"{str(e)[:200]}")

    async def _do_post(self, req: Request) -> Response:
        t0 = time.monotonic()
        path = req.path
        if not (path == "/score" or path.startswith("/score/")):
            return Response.error(404, "POST /score[/<model>]")
        body = req.body or b"{}"
        ctype = (req.header("content-type") or "").split(";")[0].strip()
        is_frame = ctype == CONTENT_TYPE_FRAME
        model_id = path[len("/score/"):] \
            if path.startswith("/score/") else ""
        if not model_id:
            if is_frame:
                # routing key from the frame's FIXED-OFFSET header — the
                # columns stay opaque bytes all the way to the replica
                try:
                    model_id = peek_model_id(body)
                except WireFormatError as e:
                    return Response(400, (json.dumps(
                        {"error": str(e)[:300]}) + "\n").encode())
            else:
                # routing key from the body's route field (popped by
                # the replica fleet anyway)
                try:
                    doc = json.loads(body or b"{}")
                    model_id = str(doc.get(self.route_field, ""))
                except ValueError:
                    model_id = ""
            if not model_id:
                return Response(400, (json.dumps(
                    {"error": "no model id (path or "
                              f"{self.route_field!r} field)"}
                ).encode()))
        fwd = {"Content-Type":
               CONTENT_TYPE_FRAME if is_frame else "application/json"}
        trace = req.header("x-trace-id")
        if trace:
            fwd["X-Trace-Id"] = trace
        # idempotency key: client header first, then in-band frame meta;
        # dispatch mints one when neither is present
        request_id = req.header("x-request-id") \
            or (peek_request_id(body) if is_frame else None)
        if request_id:
            fwd["X-Request-Id"] = str(request_id)[:128]
        status, rheaders, payload, rid = \
            await self._http.run_blocking(
                self.dispatch, model_id, body, fwd)
        self.metrics.record(rid, status, time.monotonic() - t0)
        extra = {k: v for k, v in rheaders.items()
                 if k.lower() in ("x-trace-id", "retry-after",
                                  "x-request-id", "x-dedupe")}
        if rid is not None:
            extra["X-Served-By"] = rid
        rtype = next((v for k, v in rheaders.items()
                      if k.lower() == "content-type"),
                     "application/json")
        return Response(status, payload, rtype, extra)

    async def _handle(self, req: Request) -> Response:
        if req.method == "GET":
            return await self._do_get(req)
        if req.method == "POST":
            return await self._do_post(req)
        return Response.error(404, f"method {req.method} unsupported")

    def start(self) -> "Router":
        if self._http is not None:
            return self
        if self.hedge and self._hedge_pool is None:
            self._hedge_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=16,
                thread_name_prefix="transmogrifai-hedge")
        from transmogrifai_tpu.serving.http import MAX_BODY_BYTES
        self._http = AsyncHTTPServer(
            self._handle, port=self._requested_port, host=self._host,
            max_body_bytes=MAX_BODY_BYTES,
            name="transmogrifai-scaleout-router").start()
        return self

    def stop(self) -> None:
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
            self._hedge_pool = None
        if self._http is None:
            return
        self._http.stop()
        self._http = None
