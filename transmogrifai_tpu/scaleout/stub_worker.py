"""Wire-protocol conformance stub: a replica that speaks the full
scale-out contract with NO model and NO jax.

``python -m transmogrifai_tpu.scaleout.stub_worker --state-dir S
--replica-id r0`` starts in ~100ms and serves:

- ``POST /score/<model>`` -> ``{"score": <deterministic value>,
  "replica": <id>, "version": <active>}`` (optional ``--latency-ms``),
- heartbeats + ``POST /admin/status|drain|swap|quit``,
- scripted failure modes (``--reject-swap``: the admin swap answers
  409 like a shadow-gate rejection — UNLESS the swap skips the gate
  with ``shadowRows: 0``, exactly like the real worker's forced
  rollback; ``--backpressure``: every score answers 503+Retry-After),
- ``X-Request-Id`` idempotency: scores carrying a request id are
  deduped through the same :class:`DedupeRing` the real serving stack
  uses, so router retry/hedge semantics can be chaos-tested without
  jax (``/admin/status`` reports the ring's counters as ``dedupe``).

Two jobs: (1) fast multi-process supervisor/router/rolling-swap tests
— spawn/kill/respawn semantics are about processes and sockets, not
about jax; (2) an operator chaos drill against a live router without
burning accelerator time. The REAL replica (``scaleout/worker.py``)
is covered by its own end-to-end test and the committed scale-out
bench; this stub exists so everything around it is cheap to exercise.

Imports only the stdlib + ``scaleout/wire.py`` + the stdlib-only
``serving/aiohttp_core.py`` event-loop HTTP core — keep it that way.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import threading
import time

from transmogrifai_tpu.scaleout import wire
from transmogrifai_tpu.scaleout.wire import ReplicaStates
from transmogrifai_tpu.serving.aiohttp_core import (
    AsyncHTTPServer, DedupeRing, Request, Response,
)

__all__ = ["main"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("scaleout stub worker")
    ap.add_argument("--state-dir", required=True)
    ap.add_argument("--replica-id", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--heartbeat-interval", type=float, default=0.2)
    ap.add_argument("--version", default="v1",
                    help="initial active version reported per model")
    ap.add_argument("--latency-ms", type=float, default=0.0)
    ap.add_argument("--reject-swap", action="store_true",
                    help="answer gated admin swaps 409 (shadow-parity "
                         "rejection analog); gate-skipped swaps "
                         "(shadowRows=0) still succeed")
    ap.add_argument("--backpressure", action="store_true",
                    help="answer every score 503 + Retry-After")
    # accepted-and-ignored real-worker flags so a supervisor configured
    # for real workers can be pointed at the stub unchanged
    ap.add_argument("--model-dir", default=None)
    ap.add_argument("--max-batch", type=int, default=0)
    args = ap.parse_args(argv)

    state = {"state": ReplicaStates.STARTING,
             "version": args.version, "swaps": [], "served": 0}
    lock = threading.Lock()
    stop = threading.Event()
    dedupe = DedupeRing()

    def reply(code, doc, extra=None) -> Response:
        return Response(code, (json.dumps(doc) + "\n").encode(),
                        "application/json", extra or {})

    def admin(action, payload) -> Response:
        if action == "status":
            with lock:
                return reply(200, {"ok": True,
                                   "replicaId": args.replica_id,
                                   "state": state["state"],
                                   "version": state["version"],
                                   "served": state["served"],
                                   "swaps": list(state["swaps"]),
                                   "dedupe": dedupe.to_json()})
        if action == "drain":
            # draining is a moment, not a destination (see the real
            # worker's _drain): quiesce instantly, back to READY
            with lock:
                state["state"] = ReplicaStates.READY
            return reply(200, {"ok": True, "drained": True})
        if action == "swap":
            gated = int(payload.get("shadowRows", 1) or 0) > 0
            if args.reject_swap and gated:
                return reply(409, {
                    "ok": False,
                    "error": "ShadowParityError: stub gate "
                             "rejection (scripted)"})
            with lock:
                old = state["version"]
                new = payload.get("version") \
                    or os.path.basename(
                        str(payload.get("path", "v?")))
                state["version"] = new
                state["swaps"].append(
                    {"from": old, "to": new, "gated": gated})
                state["state"] = ReplicaStates.READY
            return reply(200, {"ok": True, "fromVersion": old,
                               "toVersion": new, "fromPath": old,
                               "modelId": payload.get("modelId")})
        if action == "quit":
            stop.set()
            return reply(200, {"ok": True, "stopping": True})
        return reply(400, {"ok": False,
                           "error": f"unknown action {action}"})

    async def handle(req: Request) -> Response:
        path = req.path
        if req.method == "GET":
            if path == "/healthz":
                with lock:
                    return reply(200, {"status": "ok",
                                       "replicaId": args.replica_id,
                                       "state": state["state"]})
            return Response.error(404, "only /healthz, POST /score")
        if req.method != "POST":
            return Response.error(404,
                                  f"method {req.method} unsupported")
        try:
            payload = json.loads(req.body or b"{}")
        except ValueError:
            payload = {}
        if path.startswith("/score"):
            if args.backpressure:
                return reply(503, {"error": "stub backpressure"},
                             {"Retry-After": "0.01"})

            async def run_score() -> Response:
                if args.latency_ms:
                    await asyncio.sleep(args.latency_ms / 1e3)
                model = path[len("/score/"):] or "default"
                with lock:
                    state["served"] += 1
                    doc = {"score": float(len(model) + len(payload)),
                           "replica": args.replica_id,
                           "version": state["version"]}
                return reply(200, doc)

            rid = req.header("x-request-id")
            if not rid:
                return await run_score()
            # idempotent path: same ring contract as the real stack —
            # cached replies are re-issued as COPIES (the connection
            # loop mutates Response.close on whatever it serves)
            loop = asyncio.get_running_loop()
            for _ in range(2):
                verdict, obj = dedupe.begin(rid)
                if verdict == "hit":
                    return Response(obj.status, obj.body, obj.ctype,
                                    {**obj.headers, "X-Dedupe": "hit"})
                if verdict == "wait":
                    done = await loop.run_in_executor(
                        None, obj.event.wait, 30.0)
                    if done:
                        continue
                    return reply(504, {"error": "duplicate of "
                                       "in-flight request timed out"})
                entry = obj
                try:
                    resp = await run_score()
                except Exception:
                    dedupe.abandon(rid, entry)
                    raise
                if 200 <= resp.status < 300:
                    dedupe.complete(rid, entry, Response(
                        resp.status, resp.body, resp.ctype,
                        dict(resp.headers)))
                else:
                    dedupe.abandon(rid, entry)
                resp.headers = {**resp.headers, "X-Dedupe": "original"}
                return resp
            return reply(504, {"error": "dedupe wait loop exhausted"})
        if path.startswith("/admin/"):
            return admin(path[len("/admin/"):], payload)
        return Response.error(404, "only /healthz, POST /score")

    server = AsyncHTTPServer(handle, port=args.port,
                             name="transmogrifai-stub-worker").start()
    port = server.port
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    with lock:
        state["state"] = ReplicaStates.READY

    def hb():
        with lock:
            return wire.write_heartbeat(args.state_dir, {
                "replicaId": args.replica_id, "pid": os.getpid(),
                "port": port, "state": state["state"],
                "models": ["stub"], "queueDepths": {},
                "counters": {"admitted": state["served"],
                             "completed": state["served"], "failed": 0},
                "postWarmupCompilesMax": 0, "artifactMapped": [],
                "startedAt": time.time()})

    hb()
    while not stop.wait(args.heartbeat_interval):
        hb()
    with lock:
        state["state"] = ReplicaStates.STOPPED
    hb()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
