"""Sharded fleet-of-fleets: multi-process serving scale-out.

One host (or many) runs N **replica workers** — each a full
``serving.FleetServer`` process with its own HTTP surface — behind a
thin **router** that consistent-hashes on model id with bounded
spillover, supervised for heartbeat liveness / crash respawn / rolling
hot-swap, and autoscaled from the SLO burn-rate and host-pressure
signals the platform already keeps. See ``docs/SERVING.md``
("Scale-out") and the module docstrings:

- :mod:`~transmogrifai_tpu.scaleout.wire` — heartbeat files + admin
  HTTP control plane (stdlib-only; the protocol contract)
- :mod:`~transmogrifai_tpu.scaleout.router` — consistent-hash front
  with spillover, markdown, retry-not-drop semantics
- :mod:`~transmogrifai_tpu.scaleout.worker` — one replica process
  (``python -m transmogrifai_tpu.scaleout.worker``)
- :mod:`~transmogrifai_tpu.scaleout.stub_worker` — jax-free protocol
  conformance stub (fast multi-process tests, chaos drills)
- :mod:`~transmogrifai_tpu.scaleout.supervisor` — spawn/respawn/drain/
  scale/rolling-swap coordination
- :mod:`~transmogrifai_tpu.scaleout.autoscaler` — SLO-burn scale-up,
  pressure-guarded scale-down
- :mod:`~transmogrifai_tpu.scaleout.artifacts` — fingerprint-keyed
  shared compiled-program artifacts (compile once, map everywhere)
- :mod:`~transmogrifai_tpu.scaleout.stack` — the assembled
  router+supervisor+autoscaler stack (CLI / runner / bench surface)
"""

_LAZY = {
    "ConsistentHashRing": ("transmogrifai_tpu.scaleout.router",
                           "ConsistentHashRing"),
    "Router": ("transmogrifai_tpu.scaleout.router", "Router"),
    "ReplicaSupervisor": ("transmogrifai_tpu.scaleout.supervisor",
                          "ReplicaSupervisor"),
    "RollingSwapError": ("transmogrifai_tpu.scaleout.supervisor",
                         "RollingSwapError"),
    "Autoscaler": ("transmogrifai_tpu.scaleout.autoscaler", "Autoscaler"),
    "ArtifactStore": ("transmogrifai_tpu.scaleout.artifacts",
                      "ArtifactStore"),
    "ScaleoutStack": ("transmogrifai_tpu.scaleout.stack", "ScaleoutStack"),
}

__all__ = list(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(name)
