"""Replica supervision: spawn, heartbeat liveness, crash respawn,
graceful scale, and the coordinated rolling hot-swap.

The supervisor owns the replica PROCESSES; the router owns the routing
table; this module wires the two together:

- **spawn**: each replica is a subprocess (``scaleout/worker.py`` by
  default; any module speaking ``scaleout/wire.py`` works — tests use
  the jax-free ``stub_worker``) with stdout/stderr captured under
  ``<state_dir>/replicas/<id>.log``. A replica joins the router only
  after its first heartbeat publishes a bound port.
- **liveness**: the monitor thread polls heartbeat files every
  ``poll_interval_s`` (chaos seam ``scaleout.heartbeat``). A stale
  heartbeat marks the replica down in the router (its in-flight
  requests retry onto ring successors — zero client drops); a dead
  process additionally **respawns** (same replica id, fresh port, the
  router re-points). A fresh ``ready`` heartbeat marks it back up.
- **scale**: ``scale_to(n)`` spawns new replicas or drains victims
  (admin drain -> SIGTERM -> join, ``kill`` only on timeout), keeping
  the ring membership in lockstep.
- **rolling hot-swap**: ``rolling_swap(model_id, ...)`` promotes a new
  version across replicas ONE at a time: the router drains the replica
  (no new traffic), the replica quiesces, its own ``FleetServer.
  hot_swap`` runs behind its shadow gate, the router marks it back up
  — so fleet-wide promotion has zero global downtime by construction.
  **Failure semantics (the tested contract): the roll HALTS and rolls
  BACK.** If any replica's gate rejects the candidate (or the swap
  fails), already-swapped replicas are forced back to the old version
  with the gate skipped (the old version is the known-good one), so
  the fleet converges on the OLD version — never a split-brain fleet
  serving two versions. A completed roll persists the durable
  ``ACTIVE.json`` alias, so respawned replicas come up on the promoted
  version.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import warnings
from typing import Optional

from transmogrifai_tpu.scaleout import wire
from transmogrifai_tpu.scaleout.wire import AdminError, ReplicaStates
from transmogrifai_tpu.utils.events import events
from transmogrifai_tpu.utils.faults import fault_point

__all__ = ["ReplicaSupervisor", "RollingSwapError", "ScaleoutMetrics"]


class RollingSwapError(RuntimeError):
    """A rolling promotion halted. ``gate_rejected`` tells a parity
    rejection from infrastructure failure; ``swapped`` lists replicas
    that had promoted before the halt and ``rolled_back`` which of
    those were forced back to the old version."""

    def __init__(self, msg: str, *, gate_rejected: bool,
                 failed_replica: str, swapped: list,
                 rolled_back: list):
        super().__init__(msg)
        self.gate_rejected = gate_rejected
        self.failed_replica = failed_replica
        self.swapped = list(swapped)
        self.rolled_back = list(rolled_back)


class ScaleoutMetrics:
    """Supervisor lifecycle counters (exported as
    ``transmogrifai_scaleout_*``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.spawns = 0
        self.respawns = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.rolls = 0
        self.roll_failures = 0
        self.rollbacks = 0
        self.rebalances = 0

    def count(self, attr: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)

    def to_json(self) -> dict:
        with self._lock:
            return {"spawns": self.spawns, "respawns": self.respawns,
                    "scaleUps": self.scale_ups,
                    "scaleDowns": self.scale_downs,
                    "rolls": self.rolls,
                    "rollFailures": self.roll_failures,
                    "rollbacks": self.rollbacks,
                    "rebalances": self.rebalances}


class _Proc:
    __slots__ = ("replica_id", "proc", "spawned_at", "respawns",
                 "down_reported")

    def __init__(self, replica_id, proc):
        self.replica_id = replica_id
        self.proc = proc
        self.spawned_at = time.time()
        self.respawns = 0
        #: the crash branch fires once per DEATH, not once per monitor
        #: tick — a permanently-dead replica (respawn budget exhausted)
        #: must not flood the flight recorder forever
        self.down_reported = False


class ReplicaSupervisor:
    """Own N replica worker processes behind one router."""

    def __init__(self, model_dir: Optional[str], state_dir: str,
                 router, *, replicas: int = 2,
                 worker_module: str = "transmogrifai_tpu.scaleout.worker",
                 worker_args: Optional[list] = None,
                 worker_env: Optional[dict] = None,
                 heartbeat_ttl_s: float = 3.0,
                 poll_interval_s: float = 0.5,
                 spawn_timeout_s: float = 120.0,
                 respawn: bool = True,
                 max_respawns_per_replica: int = 5,
                 drain_timeout_s: float = 30.0,
                 rebalance_skew: float = 2.0,
                 rebalance_cooldown_s: float = 10.0):
        self.model_dir = model_dir
        self.state_dir = state_dir
        self.router = router
        self.desired_replicas = int(replicas)
        self.worker_module = worker_module
        self.worker_args = list(worker_args or [])
        self.worker_env = dict(worker_env or {})
        self.heartbeat_ttl_s = float(heartbeat_ttl_s)
        self.poll_interval_s = float(poll_interval_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.respawn = bool(respawn)
        self.max_respawns_per_replica = int(max_respawns_per_replica)
        self.drain_timeout_s = float(drain_timeout_s)
        #: trigger a load-weighted ring rebalance when the router's
        #: primary-load skew (max/mean) exceeds this; <= 1.0 disables.
        #: Cooldown keeps successive ticks from thrashing the ring
        #: while the damped re-weighting converges
        self.rebalance_skew = float(rebalance_skew)
        self.rebalance_cooldown_s = float(rebalance_cooldown_s)
        self._last_rebalance = 0.0
        self.metrics = ScaleoutMetrics()
        self._procs: dict[str, _Proc] = {}
        self._lock = threading.RLock()
        self._seq = 0
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- spawning -------------------------------------------------------------
    def _next_id(self) -> str:
        with self._lock:
            rid = f"r{self._seq}"
            self._seq += 1
            return rid

    def _worker_cmd(self, replica_id: str) -> list:
        cmd = [sys.executable, "-m", self.worker_module,
               "--state-dir", self.state_dir,
               "--replica-id", replica_id]
        if self.model_dir is not None:
            cmd += ["--model-dir", self.model_dir]
        return cmd + self.worker_args

    def _spawn(self, replica_id: str, respawn_of: bool = False) -> _Proc:
        log_dir = os.path.join(self.state_dir, wire.HEARTBEAT_DIRNAME)
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"{replica_id}.log")
        env = dict(os.environ)
        # the worker inherits the SUPERVISOR's import environment: the
        # parent's full sys.path rides in PYTHONPATH so (a) the
        # framework itself is importable from any cwd (source-tree runs
        # outside the repo would respawn-loop on ModuleNotFoundError)
        # and (b) `load_model` can resolve CUSTOM stage classes from
        # wherever the operator's deployment put their modules — if the
        # control process can load the model, its replicas can too
        import transmogrifai_tpu
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(transmogrifai_tpu.__file__)))
        paths = [pkg_root] + [p for p in sys.path if p]
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys(paths))    # de-duped, order-preserving
        env.update(self.worker_env)
        with open(log_path, "ab") as log_fh:
            proc = subprocess.Popen(
                self._worker_cmd(replica_id), stdout=log_fh,
                stderr=subprocess.STDOUT, env=env,
                start_new_session=True)
        entry = _Proc(replica_id, proc)
        with self._lock:
            prev = self._procs.get(replica_id)
            if prev is not None:
                entry.respawns = prev.respawns + (1 if respawn_of else 0)
            self._procs[replica_id] = entry
        self.metrics.count("respawns" if respawn_of else "spawns")
        events.emit("scaleout.replica_spawned", replica=replica_id,
                    pid=proc.pid, respawn=respawn_of)
        return entry

    def _wait_ready(self, replica_id: str,
                    timeout_s: Optional[float] = None) -> Optional[dict]:
        """Poll for the replica's first fresh heartbeat carrying a bound
        port; registers it with the router. None on timeout/exit."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.spawn_timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                entry = self._procs.get(replica_id)
            if entry is not None and entry.proc.poll() is not None:
                return None     # died during startup; monitor respawns
            hb = wire.read_heartbeats(self.state_dir).get(replica_id)
            if hb and hb.get("port") \
                    and wire.is_fresh(hb, self.heartbeat_ttl_s) \
                    and self._hb_pid_matches(hb, entry) \
                    and hb.get("state") in (ReplicaStates.READY,
                                            ReplicaStates.SWAPPING):
                self.router.set_replica(replica_id, hb["port"])
                return hb
            time.sleep(0.05)
        return None

    @staticmethod
    def _hb_pid_matches(hb: dict, entry: Optional["_Proc"]) -> bool:
        """A killed replica's heartbeat FILE outlives it and stays
        fresh for up to a TTL — a respawn must not read the dead
        process's port as its own readiness. The heartbeat's pid is
        the disambiguator."""
        if entry is None:
            return True
        pid = hb.get("pid")
        return pid is None or pid == entry.proc.pid

    # -- lifecycle ------------------------------------------------------------
    def start(self, wait_ready: bool = True) -> "ReplicaSupervisor":
        for _ in range(self.desired_replicas):
            self._spawn(self._next_id())
        if wait_ready:
            for rid in self.replica_ids():
                if self._wait_ready(rid) is None:
                    warnings.warn(
                        f"scaleout: replica {rid} did not become ready "
                        f"within {self.spawn_timeout_s:.0f}s (see "
                        f"{self.state_dir}/replicas/{rid}.log)",
                        RuntimeWarning)
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            name="transmogrifai-scaleout-monitor", daemon=True)
        self._monitor.start()
        return self

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            entries = list(self._procs.values())
        for entry in entries:
            self._stop_replica(entry, drain=drain)
        with self._lock:
            self._procs.clear()

    def _stop_replica(self, entry: _Proc, drain: bool = True) -> None:
        """Graceful replica stop: router out first, then SIGTERM (the
        worker drains in-flight), kill only on timeout."""
        self.router.set_draining(entry.replica_id)
        if entry.proc.poll() is None:
            try:
                entry.proc.terminate()      # SIGTERM: worker drains
                entry.proc.wait(timeout=self.drain_timeout_s)
            except subprocess.TimeoutExpired:
                warnings.warn(
                    f"scaleout: replica {entry.replica_id} ignored "
                    "SIGTERM; killing", RuntimeWarning)
                entry.proc.kill()
                entry.proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 — already-dead races (failure-ok)
                pass
        self.router.remove_replica(entry.replica_id)
        wire.clear_heartbeat(self.state_dir, entry.replica_id)
        events.emit("scaleout.replica_stopped",
                    replica=entry.replica_id)

    def replica_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._procs,
                          key=lambda r: int(r[1:]) if r[1:].isdigit()
                          else 0)

    def replica_count(self) -> int:
        with self._lock:
            return len(self._procs)

    # -- liveness monitor -----------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                fault_point("scaleout.heartbeat")
                self._tick()
            except Exception as e:  # noqa: BLE001 — the monitor must survive
                from transmogrifai_tpu.utils.faults import (
                    SimulatedPreemption,
                )
                if isinstance(e, SimulatedPreemption):
                    raise   # a preempted supervisor dies, not degrades
                warnings.warn(
                    f"scaleout: monitor tick failed ({type(e).__name__}"
                    f": {e})", RuntimeWarning)

    def _tick(self) -> None:
        heartbeats = wire.read_heartbeats(self.state_dir)
        with self._lock:
            entries = list(self._procs.values())
        for entry in entries:
            rid = entry.replica_id
            hb = heartbeats.get(rid)
            alive = entry.proc.poll() is None
            fresh = hb is not None and wire.is_fresh(
                hb, self.heartbeat_ttl_s)
            state = (hb or {}).get("state")
            if not alive:
                # crash (kill -9, OOM-kill, bug): out of routing NOW,
                # respawn if budgeted — the router already retried the
                # requests that discovered the death. Transition-edged:
                # a permanently-dead replica is reported once, not once
                # per tick.
                if entry.down_reported:
                    continue
                entry.down_reported = True
                self.router.mark_down(rid, reason="process exited "
                                      f"rc={entry.proc.poll()}")
                events.emit("scaleout.replica_down", replica=rid,
                            returncode=entry.proc.poll())
                if self.respawn and not self._stop.is_set():
                    if entry.respawns >= self.max_respawns_per_replica:
                        warnings.warn(
                            f"scaleout: replica {rid} exceeded "
                            f"{self.max_respawns_per_replica} respawns; "
                            "leaving it down", RuntimeWarning)
                        continue
                    with self._lock:
                        # a scale-down/stop may have REMOVED this
                        # replica while the tick was blocked (e.g. in
                        # another replica's _wait_ready): respawning a
                        # deliberately-retired replica would overshoot
                        # desired_replicas and fight the autoscaler
                        if self._procs.get(rid) is not entry:
                            continue
                    self._spawn(rid, respawn_of=True)
                    self._wait_ready(rid)
                continue
            entry.down_reported = False
            if not fresh:
                # alive but silent: hung or thrashing — stop routing to
                # it; it rejoins on its next fresh ready heartbeat
                self.router.mark_down(rid, reason="stale heartbeat")
                continue
            if not self._hb_pid_matches(hb, entry):
                # a fresh-looking heartbeat from the PREVIOUS process
                # of this replica id (killed within the TTL): the new
                # process hasn't published yet — not routable
                self.router.mark_down(rid, reason="heartbeat from "
                                                  "dead predecessor")
                continue
            if state == ReplicaStates.READY:
                if hb.get("port"):
                    self.router.set_replica(rid, hb["port"])
                self.router.mark_up(rid)
            elif state in (ReplicaStates.DRAINING,
                           ReplicaStates.STOPPED):
                self.router.set_draining(rid)
        self._maybe_rebalance()

    def _maybe_rebalance(self) -> None:
        """Skew-aware placement: when the router's per-model EWMA loads
        pile onto one primary past ``rebalance_skew`` (max/mean), take
        one damped re-weighting step — the ring rebalances on LOAD
        skew, not just membership change. Cooldown-limited so the EWMA
        can reflect the new placement before the next step."""
        if self.rebalance_skew <= 1.0:
            return
        load_skew = getattr(self.router, "load_skew", None)
        rebalance = getattr(self.router, "rebalance", None)
        if load_skew is None or rebalance is None:
            return
        if len(getattr(self.router, "ring", ())) < 2:
            return      # one primary owns everything by construction
        now = time.time()
        if now - self._last_rebalance < self.rebalance_cooldown_s:
            return
        skew = load_skew()
        if skew <= self.rebalance_skew:
            return
        self._last_rebalance = now
        if rebalance():
            self.metrics.count("rebalances")
            events.emit("scaleout.rebalanced", skew=round(skew, 3))

    # -- scaling --------------------------------------------------------------
    def scale_to(self, n: int, wait_ready: bool = True) -> int:
        """Converge on ``n`` replicas. Scale-up spawns; scale-down
        drains the newest replicas first (oldest keep their warm
        caches). Returns the resulting count."""
        n = int(n)
        with self._lock:
            current = len(self._procs)
        if n > current:
            self.metrics.count("scale_ups")
            events.emit("scaleout.scale", direction="up",
                        fromReplicas=current, toReplicas=n)
            new_ids = [self._next_id() for _ in range(n - current)]
            for rid in new_ids:
                self._spawn(rid)
            if wait_ready:
                for rid in new_ids:
                    self._wait_ready(rid)
        elif n < current:
            self.metrics.count("scale_downs")
            events.emit("scaleout.scale", direction="down",
                        fromReplicas=current, toReplicas=n)
            victims = self.replica_ids()[n:]
            for rid in victims:
                with self._lock:
                    entry = self._procs.pop(rid, None)
                if entry is not None:
                    self._drain_admin(rid)
                    self._stop_replica(entry)
        self.desired_replicas = n
        return self.replica_count()

    def _drain_admin(self, replica_id: str) -> None:
        """Best-effort admin drain (quiesce stragglers) before SIGTERM."""
        hb = wire.read_heartbeats(self.state_dir).get(replica_id)
        if hb and hb.get("port"):
            try:
                wire.admin_call(hb["port"], "drain",
                                {"timeoutS": self.drain_timeout_s},
                                timeout_s=self.drain_timeout_s + 5)
            except AdminError:
                pass

    # -- rolling hot-swap -----------------------------------------------------
    def rolling_swap(self, model_id: str, *,
                     version: Optional[str] = None,
                     path: Optional[str] = None,
                     tolerance: Optional[float] = None,
                     shadow_rows: Optional[int] = None) -> dict:
        """Promote ``version``/``path`` of ``model_id`` across every
        live replica, one at a time, each behind its own shadow gate
        (see the module docstring for the halt-and-roll-back failure
        semantics). Returns a roll report."""
        if version is None and path is None:
            raise ValueError("rolling_swap needs a version or a path")
        t0 = time.monotonic()
        heartbeats = wire.read_heartbeats(self.state_dir)
        with self._lock:
            procs = dict(self._procs)
        targets = [rid for rid in self.replica_ids()
                   if heartbeats.get(rid, {}).get("port")
                   and wire.is_fresh(heartbeats[rid],
                                     self.heartbeat_ttl_s)
                   and self._hb_pid_matches(heartbeats[rid],
                                            procs.get(rid))]
        if not targets:
            raise RuntimeError("rolling_swap: no live replicas")
        swapped: list[tuple] = []      # (replica_id, swap report)
        events.emit("scaleout.roll_started", model=model_id,
                    version=version, path=path, replicas=targets)
        for rid in targets:
            port = heartbeats[rid]["port"]
            self.router.set_draining(rid)
            pre_state = self._pre_swap_state(port, model_id)
            try:
                fault_point("scaleout.roll")
                self._admin_drain_quiet(port)
                payload: dict = {"modelId": model_id}
                if version is not None:
                    payload["version"] = version
                if path is not None:
                    payload["path"] = path
                if tolerance is not None:
                    payload["tolerance"] = tolerance
                if shadow_rows is not None:
                    payload["shadowRows"] = shadow_rows
                report = wire.admin_call(port, "swap", payload,
                                         timeout_s=self.drain_timeout_s
                                         + 60)
            except Exception as e:  # noqa: BLE001 — halt the roll, converge back
                from transmogrifai_tpu.utils.faults import (
                    FaultHarnessError,
                )
                self.router.mark_up(rid)   # still serving the OLD version
                gate = isinstance(e, AdminError) and e.status == 409
                if not gate and pre_state is not None:
                    # a TRANSPORT-level failure (timeout, connection
                    # death) is ambiguous: the replica's in-flight
                    # hot_swap may still COMPLETE after this halt,
                    # leaving it alone on the new version — the exact
                    # split-brain the rollback exists to prevent. Force
                    # it back using the pre-swap state captured above
                    # (an "already active" refusal from a replica that
                    # never swapped is the harmless outcome).
                    swapped.append((rid, pre_state))
                rolled_back = self._rollback(model_id, swapped)
                self.metrics.count("roll_failures")
                events.emit("scaleout.roll_failed", model=model_id,
                            replica=rid, gateRejected=gate,
                            swapped=[r for r, _ in swapped],
                            rolledBack=rolled_back,
                            error=f"{type(e).__name__}: {str(e)[:200]}")
                err = RollingSwapError(
                    f"rolling swap of {model_id!r} halted at replica "
                    f"{rid}: {e}; {len(rolled_back)}/{len(swapped)} "
                    "already-swapped replica(s) rolled back — fleet "
                    "converges on the old version",
                    gate_rejected=gate, failed_replica=rid,
                    swapped=[r for r, _ in swapped],
                    rolled_back=rolled_back)
                if isinstance(e, FaultHarnessError):
                    # chaos-harness errors surface as themselves, with
                    # the converge-back already done above
                    raise e
                raise err from e
            self.router.mark_up(rid)
            swapped.append((rid, report))
            events.emit("scaleout.roll_step", model=model_id,
                        replica=rid,
                        toVersion=report.get("toVersion"))
        self._persist_alias(model_id, version, path, swapped)
        wall = time.monotonic() - t0
        self.metrics.count("rolls")
        events.emit("scaleout.roll", model=model_id, version=version,
                    replicas=[r for r, _ in swapped],
                    wallSeconds=round(wall, 6))
        return {"modelId": model_id, "version": version, "path": path,
                "replicas": [r for r, _ in swapped],
                "wallSeconds": round(wall, 6),
                "reports": {r: rep for r, rep in swapped}}

    def _admin_drain_quiet(self, port: int) -> None:
        try:
            wire.admin_call(port, "drain", {"timeoutS": 10.0},
                            timeout_s=20.0)
        except AdminError:
            pass    # drain is belt-and-braces; the swap itself drains

    def _pre_swap_state(self, port: int,
                        model_id: str) -> Optional[dict]:
        """The replica's ACTIVE version + path for ``model_id`` before
        its swap — the rollback recipe for the ambiguous transport-
        failure case (see rolling_swap). None when unreadable."""
        try:
            st = wire.admin_call(port, "status", timeout_s=20.0)
        except AdminError:
            return None
        for m in st.get("models", []):
            if m.get("modelId") == model_id and m.get("active"):
                return {"fromVersion": m.get("version"),
                        "fromPath": m.get("path")}
        return None

    def _rollback(self, model_id: str, swapped: list) -> list:
        """Force already-swapped replicas back to the old version, gate
        skipped (``shadowRows: 0`` — the old version is the known-good
        one and a symmetric parity gate would reject the restore for
        exactly the divergence that aborted the roll)."""
        rolled_back: list = []
        heartbeats = wire.read_heartbeats(self.state_dir)
        for rid, report in reversed(swapped):
            from_path = report.get("fromPath")
            from_version = report.get("fromVersion")
            port = heartbeats.get(rid, {}).get("port")
            if port is None or (from_path is None
                                and from_version is None):
                warnings.warn(
                    f"scaleout: cannot roll back replica {rid} (no "
                    "port/old-version info); it keeps the NEW version "
                    "until the next roll", RuntimeWarning)
                continue
            payload = {"modelId": model_id, "shadowRows": 0}
            if from_path is not None:
                payload["path"] = from_path
            else:
                payload["version"] = from_version
            try:
                self.router.set_draining(rid)
                wire.admin_call(port, "swap", payload,
                                timeout_s=self.drain_timeout_s + 60)
                rolled_back.append(rid)
                self.metrics.count("rollbacks")
            except AdminError as e:
                warnings.warn(
                    f"scaleout: rollback of replica {rid} failed "
                    f"({e}); it keeps the NEW version", RuntimeWarning)
            finally:
                self.router.mark_up(rid)
        return rolled_back

    def _persist_alias(self, model_id: str, version: Optional[str],
                       path: Optional[str], swapped: list) -> None:
        """Persist the durable ACTIVE alias after a COMPLETED roll so a
        respawned replica serves the promoted version. Only meaningful
        for the versioned ``<model_dir>/<id>/<version>/`` layout."""
        if self.model_dir is None:
            return
        ver = version
        if ver is None and path is not None:
            parent = os.path.dirname(os.path.normpath(path))
            if os.path.basename(parent) == model_id and \
                    os.path.dirname(parent) == \
                    os.path.normpath(self.model_dir):
                ver = os.path.basename(os.path.normpath(path))
        if ver is None and swapped:
            ver = swapped[-1][1].get("toVersion")
            # a path outside the register layout has no durable name —
            # respawns keep activating per ACTIVE/lowest as before
            if path is not None:
                return
        if ver:
            from transmogrifai_tpu.serving.registry import (
                write_active_alias,
            )
            try:
                write_active_alias(self.model_dir, model_id, ver)
            except OSError as e:
                warnings.warn(
                    f"scaleout: could not persist ACTIVE alias "
                    f"({type(e).__name__}: {e}); respawned replicas "
                    "will serve the pre-roll version", RuntimeWarning)

    # -- observability --------------------------------------------------------
    def heartbeats(self) -> dict:
        return wire.read_heartbeats(self.state_dir)

    def queue_ratio(self, queue_capacity: Optional[int] = None) -> float:
        """Mean fill ratio of replica admission queues (the autoscaler's
        load signal). Uses each heartbeat's own ``queueCapacity`` when
        present, else ``queue_capacity``."""
        heartbeats = self.heartbeats()
        ratios: list[float] = []
        for hb in heartbeats.values():
            if not wire.is_fresh(hb, self.heartbeat_ttl_s):
                continue
            depths = hb.get("queueDepths") or {}
            cap = hb.get("queueCapacity") or queue_capacity
            if not cap:
                continue
            total = sum(int(v) for v in depths.values()) \
                if isinstance(depths, dict) else 0
            ratios.append(min(total / float(cap), 1.0))
        return sum(ratios) / len(ratios) if ratios else 0.0

    def to_json(self) -> dict:
        with self._lock:
            procs = {rid: {"pid": p.proc.pid,
                           "alive": p.proc.poll() is None,
                           "respawns": p.respawns,
                           "spawnedAt": p.spawned_at}
                     for rid, p in self._procs.items()}
        return {"desiredReplicas": self.desired_replicas,
                "replicas": procs,
                "metrics": self.metrics.to_json()}
