"""The assembled scale-out stack: router + supervisor (+ autoscaler)
as one object — the surface ``cli scaleout``, the runner's SCALEOUT
mode, the bench and the tests all drive.

Startup order matters and lives here so every caller gets it right:
the router binds first (clients can connect and get honest 503s while
replicas warm), replicas spawn and join as their heartbeats publish
bound ports, artifact manifests publish BEFORE the spawn when warm
rows are given (so even the first replica warms through the shared
layer), and the autoscaler starts last.
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Optional

__all__ = ["ScaleoutStack"]


class ScaleoutStack:
    """One-call scale-out serving: ``ScaleoutStack(model_dir,
    state_dir, replicas=4).start()``."""

    def __init__(self, model_dir: str, state_dir: str, *,
                 replicas: int = 2, port: int = 0,
                 host: str = "127.0.0.1", spill: int = 2,
                 slo=None, autoscale: bool = False,
                 min_replicas: int = 1, max_replicas: int = 8,
                 autoscale_interval_s: float = 5.0,
                 cooldown_s: float = 30.0,
                 warm_rows: Optional[dict] = None,
                 worker_module: str =
                 "transmogrifai_tpu.scaleout.worker",
                 worker_args: Optional[list] = None,
                 worker_env: Optional[dict] = None,
                 heartbeat_ttl_s: float = 3.0,
                 spawn_timeout_s: float = 180.0,
                 use_artifacts: bool = True):
        from transmogrifai_tpu.scaleout.autoscaler import Autoscaler
        from transmogrifai_tpu.scaleout.router import Router
        from transmogrifai_tpu.scaleout.supervisor import (
            ReplicaSupervisor,
        )
        self.model_dir = model_dir
        self.state_dir = state_dir
        self.use_artifacts = bool(use_artifacts)
        #: model id -> one representative request row; published as
        #: artifact manifests before the first replica spawns
        self.warm_rows = dict(warm_rows or {})
        self.router = Router(port=port, host=host, spill=spill, slo=slo)
        args = list(worker_args or [])
        if not use_artifacts and "--no-artifacts" not in args:
            args.append("--no-artifacts")
        self.supervisor = ReplicaSupervisor(
            model_dir, state_dir, self.router, replicas=replicas,
            worker_module=worker_module, worker_args=args,
            worker_env=worker_env, heartbeat_ttl_s=heartbeat_ttl_s,
            spawn_timeout_s=spawn_timeout_s)
        self.autoscaler = Autoscaler(
            self.supervisor, min_replicas=min_replicas,
            max_replicas=max_replicas,
            interval_s=autoscale_interval_s,
            cooldown_s=cooldown_s) if autoscale else None
        self.started_at: Optional[float] = None

    # -- artifact publication -------------------------------------------------
    def publish_artifacts(self) -> int:
        """Publish warmup manifests for ``warm_rows`` WITHOUT loading
        any model (fingerprints hash the saved bytes): the operator-prep
        step that lets replica #1 already warm through the shared
        layer. Returns the number of manifests published."""
        if not self.warm_rows or not self.use_artifacts:
            return 0
        from transmogrifai_tpu.checkpoint import model_fingerprint
        from transmogrifai_tpu.scaleout.artifacts import ArtifactStore
        from transmogrifai_tpu.serialization import MODEL_JSON
        from transmogrifai_tpu.serving.registry import read_active_alias
        store = ArtifactStore(self.model_dir)
        n = 0
        for model_id, row in self.warm_rows.items():
            id_dir = os.path.join(self.model_dir, model_id)
            path = None
            if os.path.exists(os.path.join(id_dir, MODEL_JSON)):
                path = id_dir
            elif os.path.isdir(id_dir):
                alias = read_active_alias(id_dir)
                versions = sorted(
                    v for v in os.listdir(id_dir)
                    if os.path.exists(os.path.join(id_dir, v,
                                                   MODEL_JSON)))
                if alias and alias in versions:
                    path = os.path.join(id_dir, alias)
                elif versions:
                    path = os.path.join(id_dir, versions[0])
            if path is None:
                warnings.warn(
                    f"scaleout: no saved model for warm row "
                    f"{model_id!r} under {self.model_dir!r}",
                    RuntimeWarning)
                continue
            fp = model_fingerprint(path=path)
            if store.publish(fp, {"modelId": model_id,
                                  "warmRow": dict(row),
                                  "publishedBy": "stack"}):
                n += 1
        return n

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "ScaleoutStack":
        self.publish_artifacts()
        self.router.start()
        self.supervisor.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        self.started_at = time.time()
        return self

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.supervisor.stop()
        self.router.stop()

    def __enter__(self) -> "ScaleoutStack":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- operations -----------------------------------------------------------
    def rolling_swap(self, model_id: str, **kwargs) -> dict:
        return self.supervisor.rolling_swap(model_id, **kwargs)

    def scale_to(self, n: int) -> int:
        return self.supervisor.scale_to(n)

    @property
    def port(self) -> Optional[int]:
        return self.router.port

    def status(self) -> dict:
        doc = {"router": {"port": self.router.port,
                          "replicas": self.router.replicas(),
                          "metrics": self.router.metrics.to_json()},
               "supervisor": self.supervisor.to_json(),
               "heartbeats": self.supervisor.heartbeats(),
               "startedAt": self.started_at}
        if self.autoscaler is not None:
            doc["autoscaler"] = self.autoscaler.to_json()
        return doc
