"""Fingerprint-keyed shared compiled-program artifacts: compile once,
map everywhere.

The cross-process analog of the in-process ``serving.fleet.
ProgramCache``: a model's fused serving programs are keyed by its
checkpoint **fingerprint** (``checkpoint.model_fingerprint``), which is
identical in every replica that loaded the same bytes — so the compile
work is shareable. Two cooperating mechanisms:

1. **shared XLA compilation cache** (the heavy lifting):
   :meth:`ArtifactStore.enable_shared_compilation_cache` points jax's
   persistent compilation cache at ``<root>/_artifacts/xla_cache``
   (thresholds dropped so every serving program caches). The FIRST
   process to compile a ``(fingerprint, layer, bucket)`` program pays
   XLA; every other replica's warmup **maps** the serialized executable
   from disk. This is AOT serialization by the backend's own format —
   no hand-rolled pickling of executables, and safely keyed by XLA on
   program + compile options + versions, so a jax upgrade misses the
   cache instead of loading an incompatible blob.
2. **warmup manifests** (the recipe): after warming, a replica
   publishes ``<root>/_artifacts/<fingerprint>.json`` through the
   ``ModelRegistry`` — which padding buckets exist and one
   representative ``warmRow`` — so later replicas (and respawns) warm
   exactly the published buckets *before taking traffic* instead of
   compiling lazily under load. Publication is atomic and idempotent;
   first writer wins.

Attribution stays **per-replica**: each worker keeps its own in-process
``ProgramCache`` + ``ServingCounters``, so insertions/evictions (and
the 0-post-warmup-compiles bound) are still accounted per replica; the
artifact layer only removes the redundant XLA work.
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Optional

from transmogrifai_tpu.utils.durable import atomic_json_dump

__all__ = ["ArtifactStore", "ARTIFACTS_DIRNAME"]

#: subdirectory of a model register root holding the artifact layer
ARTIFACTS_DIRNAME = "_artifacts"


class ArtifactStore:
    """Filesystem program-artifact store under a model register root
    (attachable to a ``ModelRegistry`` via ``attach_artifacts``)."""

    def __init__(self, root: str):
        #: the model register root; artifacts live in a sibling-proof
        #: subdir so ``register_dir`` scans never mistake it for a model
        self.root = root
        self.dir = os.path.join(root, ARTIFACTS_DIRNAME)
        self.cache_dir = os.path.join(self.dir, "xla_cache")
        self._cache_enabled = False

    # -- manifests -----------------------------------------------------------
    def manifest_path(self, fingerprint: str) -> str:
        return os.path.join(self.dir, f"{fingerprint}.json")

    def publish(self, fingerprint: str, doc: dict) -> Optional[str]:
        """Publish one model's warmup manifest (idempotent: the first
        writer wins — every replica of one fingerprint would publish
        the same recipe). Best-effort: a full disk must not fail the
        replica that just warmed successfully."""
        path = self.manifest_path(fingerprint)
        if os.path.exists(path):
            return path
        try:
            os.makedirs(self.dir, exist_ok=True)
            doc = dict(doc)
            doc.setdefault("fingerprint", fingerprint)
            doc.setdefault("publishedAt", time.time())
            atomic_json_dump(doc, path)
            return path
        except OSError as e:
            warnings.warn(
                f"artifact store: publish of {fingerprint[:12]} failed "
                f"({type(e).__name__}: {e}); replicas will warm without "
                "the manifest", RuntimeWarning)
            return None

    def get(self, fingerprint: str) -> Optional[dict]:
        try:
            with open(self.manifest_path(fingerprint)) as fh:
                return json.load(fh)
        except FileNotFoundError:
            return None
        except Exception as e:  # noqa: BLE001 — corrupt manifest: warn, warm lazily
            warnings.warn(
                f"artifact store: corrupt manifest for "
                f"{fingerprint[:12]} ({type(e).__name__}: {e}); warming "
                "without it", RuntimeWarning)
            return None

    def list(self) -> list[str]:
        """Published fingerprints."""
        try:
            return sorted(n[:-5] for n in os.listdir(self.dir)
                          if n.endswith(".json"))
        except FileNotFoundError:
            return []

    # -- shared XLA compilation cache ----------------------------------------
    def enable_shared_compilation_cache(self) -> bool:
        """Point jax's persistent compilation cache at the shared
        artifact dir (idempotent). Must run before the process's first
        serving compile to be effective. Returns False (with a warning)
        when this jax build refuses — the stack still works, each
        replica just compiles for itself."""
        if self._cache_enabled:
            return True
        try:
            import jax
            os.makedirs(self.cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", self.cache_dir)
            # serving programs are small and compile fast — cache them
            # all (the default thresholds exist for interactive use)
            for knob, value in (
                    ("jax_persistent_cache_min_compile_time_secs", 0),
                    ("jax_persistent_cache_min_entry_size_bytes", -1)):
                try:
                    jax.config.update(knob, value)
                except Exception:  # noqa: BLE001 — knob absent on this jax (failure-ok)
                    pass
            self._cache_enabled = True
            return True
        except Exception as e:  # noqa: BLE001 — cache is an optimization, not a dependency
            warnings.warn(
                f"artifact store: shared compilation cache unavailable "
                f"({type(e).__name__}: {e}); every replica compiles for "
                "itself", RuntimeWarning)
            return False

    def to_json(self) -> dict:
        cache_entries = 0
        try:
            cache_entries = sum(1 for n in os.listdir(self.cache_dir)
                                if n.endswith("-cache"))
        except OSError:
            pass
        return {"dir": self.dir, "manifests": len(self.list()),
                "enabledInThisProcess": self._cache_enabled,
                "sharedCacheEntries": cache_entries}
