"""One scale-out replica: a full ``serving.FleetServer`` process behind
the wire protocol.

``python -m transmogrifai_tpu.scaleout.worker --model-dir models/
--state-dir scale_state/ --replica-id r0`` runs the EXISTING fleet
server unmodified — per-model lanes, shared in-process program cache,
shadow-gated hot swap — and adds the scale-out contract around it:

- binds its HTTP surface on an **ephemeral port** (``--port 0``) and
  publishes the bound port through its heartbeat file, so N replicas on
  one host never race on a fixed port;
- **heartbeats** every ``--heartbeat-interval`` seconds (atomic
  rewrite; see ``scaleout/wire.py``) with lifecycle state, queue
  depths, serving counters and the post-warmup compile bound;
- serves the **admin control plane** (``POST /admin/status|drain|swap|
  quit``) the supervisor drives drains and rolling promotions through;
- maps the **shared compiled-program artifact layer**: the register
  root's ``_artifacts/`` XLA cache is enabled before the first compile
  and published warmup manifests decide which padding buckets warm
  before traffic — a program any replica compiled before is loaded,
  not recompiled (per-replica cache/counter attribution unchanged);
- honors the durable ``ACTIVE.json`` alias (``serving/registry.py``):
  a replica respawned after a fleet-wide rolling promotion comes back
  serving the promoted version, not v1;
- drains gracefully on **SIGTERM** (finish in-flight requests, final
  ``stopped`` heartbeat) — the supervisor's scale-down and the
  operator's ^C both exit without dropping an admitted request;
- optionally runs **multi-tenant** (``--tenancy`` +
  ``--tenancy-ram-budget-mb`` / ``--tenant-rate`` /
  ``--tenancy-prewarm-top-k``): thousands of checkpoints register
  COLD and demand-page through the router hop, with the per-tenant
  fairness gate answering floods 503 + Retry-After at the front door;
- deduplicates retried requests by ``X-Request-Id`` (the serving
  stack's :class:`DedupeRing`); ``/admin/status`` reports the ring's
  counters so a chaos drill can prove zero double-scores fleet-wide.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
import warnings
from typing import Optional

from transmogrifai_tpu.scaleout import wire
from transmogrifai_tpu.scaleout.wire import ReplicaStates
from transmogrifai_tpu.utils.events import events

__all__ = ["ReplicaWorker", "main"]


class ReplicaWorker:
    """The in-process body of one replica (the subprocess entry point,
    but embeddable in tests)."""

    def __init__(self, model_dir: str, state_dir: str, replica_id: str,
                 *, port: int = 0, host: str = "127.0.0.1",
                 heartbeat_interval_s: float = 1.0,
                 use_artifacts: bool = True,
                 warmup_rows: Optional[dict] = None,
                 **fleet_kwargs):
        from transmogrifai_tpu.scaleout.artifacts import ArtifactStore
        from transmogrifai_tpu.serving.fleet import FleetServer
        from transmogrifai_tpu.serving.registry import ModelRegistry
        self.model_dir = model_dir
        self.state_dir = state_dir
        self.replica_id = replica_id
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._host = host
        self._port = int(port)
        self.state = ReplicaStates.STARTING
        self._stop = threading.Event()
        self._state_lock = threading.Lock()
        self.artifacts = ArtifactStore(model_dir) if use_artifacts \
            else None
        registry = ModelRegistry()
        if self.artifacts is not None:
            registry.attach_artifacts(self.artifacts)
        self.fleet = FleetServer(registry=registry, **fleet_kwargs)
        self.http = None
        #: explicit warm rows (e.g. --warmup file) — merged over the
        #: artifact manifests' rows
        self._warmup_rows = dict(warmup_rows or {})
        self._artifact_mapped: list = []
        self.started_at = time.time()

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "ReplicaWorker":
        from transmogrifai_tpu.serving.http import MetricsServer
        from transmogrifai_tpu.utils.prometheus import build_registry
        if self.artifacts is not None:
            # BEFORE the first compile: later is silently ineffective
            self.artifacts.enable_shared_compilation_cache()
        entries = self.fleet.register_dir(self.model_dir)
        if not entries:
            raise ValueError(
                f"replica {self.replica_id}: no saved models under "
                f"{self.model_dir!r}")
        warm = self._collect_warmup_rows()
        self.fleet.start(warmup_rows=warm)
        self._publish_artifacts(warm)
        registry = build_registry(fleet=self.fleet)
        self.http = MetricsServer(
            render_fn=registry.render, health_fn=self.health,
            score_fn=self.fleet._http_score,
            # --wire binary (the default) publishes the columnar frame
            # wire on this replica's own port — the router's data plane;
            # without it every frame request bounces 400 at the replica
            frame_fn=self.fleet._http_frame
            if self.fleet.wire == "binary" else None,
            control_fn=self.control,
            port=self._port, host=self._host).start()
        self._set_state(ReplicaStates.READY)
        self.heartbeat()
        events.emit("scaleout.replica_ready", replica=self.replica_id,
                    port=self.http.port,
                    models=self.fleet.registry.model_ids())
        return self

    def _collect_warmup_rows(self) -> dict:
        """model id -> representative row: explicit rows first, then the
        shared artifact manifests (the 'map everywhere' half: warm the
        published buckets before traffic, hitting the shared XLA
        cache)."""
        warm = dict(self._warmup_rows)
        for model_id in self.fleet.registry.model_ids():
            if model_id in warm:
                continue
            version = self.fleet.registry.active_version(model_id)
            if version is None:
                continue
            entry = self.fleet.registry.get(model_id, version)
            manifest = self.fleet.registry.program_artifact(
                entry.fingerprint)
            if manifest and isinstance(manifest.get("warmRow"), dict):
                warm[model_id] = dict(manifest["warmRow"])
                self._artifact_mapped.append(model_id)
        return warm

    def _publish_artifacts(self, warm: dict) -> None:
        """Publish manifests for models this replica warmed from an
        explicit row (first replica up publishes; later replicas map)."""
        for model_id, row in warm.items():
            version = self.fleet.registry.active_version(model_id)
            if version is None:
                continue
            entry = self.fleet.registry.get(model_id, version)
            self.fleet.registry.publish_program_artifact(
                entry.fingerprint,
                {"modelId": model_id, "version": version,
                 "warmRow": row, "publishedBy": self.replica_id})

    def run(self) -> int:
        """Start, then heartbeat until stopped (SIGTERM / admin quit)."""
        signal.signal(signal.SIGTERM, lambda *_: self.request_stop())
        try:
            self.start()
        except Exception as e:  # noqa: BLE001 — a failed start must report, not hang the supervisor
            print(f"# replica {self.replica_id}: start failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            self._set_state(ReplicaStates.STOPPED)
            self.heartbeat(best_effort=True)
            return 1
        print(f"# replica {self.replica_id}: serving "
              f"{self.fleet.registry.model_ids()} on "
              f"{self._host}:{self.http.port}", file=sys.stderr)
        while not self._stop.wait(self.heartbeat_interval_s):
            self.heartbeat(best_effort=True)
        self._shutdown()
        return 0

    def request_stop(self) -> None:
        self._stop.set()

    def _shutdown(self) -> None:
        """Graceful SIGTERM/quit path: drain in-flight, final
        heartbeat."""
        self._set_state(ReplicaStates.DRAINING)
        self.heartbeat(best_effort=True)
        try:
            self.fleet.stop(drain=True)
        finally:
            if self.http is not None:
                self.http.stop()
                self.http = None
            self._set_state(ReplicaStates.STOPPED)
            self.heartbeat(best_effort=True)

    def _set_state(self, state: str) -> None:
        with self._state_lock:
            self.state = state

    # -- wire surface ---------------------------------------------------------
    def heartbeat(self, best_effort: bool = False) -> Optional[str]:
        try:
            totals = {"admitted": 0, "completed": 0, "failed": 0}
            post_warmup_max = 0
            lanes = self.fleet.active_lanes() \
                if self.state != ReplicaStates.STOPPED else {}
            for lane in lanes.values():
                m = lane.metrics
                totals["admitted"] += m.admitted
                totals["completed"] += m.completed
                totals["failed"] += m.failed
                per = lane.post_warmup_compiles()
                if per:
                    post_warmup_max = max(post_warmup_max,
                                          max(per.values()))
            doc = {
                "replicaId": self.replica_id,
                "pid": os.getpid(),
                "port": self.http.port if self.http else None,
                "state": self.state,
                "models": self.fleet.registry.model_ids(),
                "queueDepths": (self.fleet.queue_depths()
                                if lanes else {}),
                "queueCapacity": next(
                    (lane.batcher.queue_capacity
                     for lane in lanes.values()), None),
                "counters": totals,
                "postWarmupCompilesMax": post_warmup_max,
                "artifactMapped": sorted(self._artifact_mapped),
                "startedAt": self.started_at,
            }
            return wire.write_heartbeat(self.state_dir, doc)
        except Exception as e:  # noqa: BLE001 — a heartbeat must not kill the replica
            if not best_effort:
                raise
            warnings.warn(
                f"replica {self.replica_id}: heartbeat write failed "
                f"({type(e).__name__}: {e})", RuntimeWarning)
            return None

    def health(self) -> dict:
        doc = self.fleet.health()
        doc["replicaId"] = self.replica_id
        doc["replicaState"] = self.state
        return doc

    def control(self, action: str, payload: dict) -> dict:
        """The admin control plane (behind ``POST /admin/<action>``)."""
        if action == "status":
            return self._status()
        if action == "drain":
            return self._drain(timeout_s=float(
                payload.get("timeoutS", 30.0)))
        if action == "swap":
            return self._swap(payload)
        if action == "quit":
            self.request_stop()
            return {"ok": True, "stopping": True}
        raise ValueError(f"unknown admin action {action!r} (one of "
                         "status, drain, swap, quit)")

    def _status(self) -> dict:
        post_warmup = {
            mid: {str(b): n
                  for b, n in lane.post_warmup_compiles().items()}
            for mid, lane in self.fleet.active_lanes().items()}
        doc = {"ok": True, "replicaId": self.replica_id,
               "state": self.state, "pid": os.getpid(),
               "models": self.fleet.registry.list(),
               "queueDepths": self.fleet.queue_depths(),
               "postWarmupCompiles": post_warmup,
               "artifactMapped": sorted(self._artifact_mapped),
               "cache": self.fleet.program_cache.to_json()}
        if self.http is not None and self.http.dedupe is not None:
            # idempotency proof surface: the chaos bench checks
            # fleet-wide sum(dedupe.scored) == distinct requests
            doc["dedupe"] = self.http.dedupe.to_json()
        if self.fleet.tenancy_store is not None:
            doc["tenancy"] = self.fleet.tenancy_store.to_json()
        return doc

    def _drain(self, timeout_s: float = 30.0) -> dict:
        """Quiesce: wait (bounded) for every lane's admission queue to
        empty. The caller (supervisor) has already stopped routing new
        traffic here; this settles the stragglers. The replica returns
        to READY when the wait ends — draining is a moment, not a
        destination: the router-side flag owns keep-away during a
        swap, and a roll that dies between drain and swap must not
        leave a healthy replica heartbeating DRAINING (unroutable)
        forever. A SIGTERM/scale-down drain is followed by process
        exit, where the brief READY re-report is moot."""
        self._set_state(ReplicaStates.DRAINING)
        self.heartbeat(best_effort=True)
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                depths = self.fleet.queue_depths()
                if not any(depths.values()):
                    return {"ok": True, "drained": True,
                            "queueDepths": depths}
                time.sleep(0.05)
            return {"ok": True, "drained": False,
                    "queueDepths": self.fleet.queue_depths()}
        finally:
            if not self._stop.is_set():
                self._set_state(ReplicaStates.READY)
                self.heartbeat(best_effort=True)

    def _swap(self, payload: dict) -> dict:
        """Hot-swap one model behind the live endpoint. ``shadowRows:
        0`` skips the parity gate — the supervisor's forced-rollback
        path (the version being restored was the known-good one)."""
        model_id = payload.get("modelId")
        if not model_id:
            raise ValueError("swap needs modelId")
        old_version = self.fleet.registry.active_version(model_id)
        old_path = None
        if old_version is not None:
            old_path = self.fleet.registry.get(
                model_id, old_version).path
        kwargs: dict = {}
        if payload.get("tolerance") is not None:
            kwargs["tolerance"] = float(payload["tolerance"])
        if payload.get("shadowRows") is not None:
            kwargs["shadow_rows"] = int(payload["shadowRows"])
        self._set_state(ReplicaStates.SWAPPING)
        self.heartbeat(best_effort=True)
        try:
            report = self.fleet.hot_swap(
                model_id, payload.get("path"),
                version=payload.get("version"), **kwargs)
        finally:
            self._set_state(ReplicaStates.READY)
            self.heartbeat(best_effort=True)
        report = dict(report)
        report["ok"] = True
        report["fromPath"] = old_path
        return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("transmogrifai_tpu scaleout worker")
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--state-dir", required=True)
    ap.add_argument("--replica-id", required=True)
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral, reported via the "
                         "heartbeat; default)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--heartbeat-interval", type=float, default=1.0)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--queue-capacity", type=int, default=256)
    ap.add_argument("--min-bucket", type=int, default=None,
                    help="smallest padding bucket (default max-batch: "
                         "ONE bucket per model keeps replica warmup to "
                         "one compile per fused layer)")
    ap.add_argument("--shadow-tolerance", type=float, default=None)
    ap.add_argument("--wire", choices=("binary", "json"),
                    default="binary",
                    help="binary (default): negotiate the columnar "
                         "frame wire alongside JSON/NDJSON on /score; "
                         "json: pin the endpoint JSON-only (frame "
                         "POSTs answer 400)")
    ap.add_argument("--no-artifacts", action="store_true",
                    help="skip the shared compiled-program artifact "
                         "layer (every replica compiles for itself)")
    ap.add_argument("--warmup", default=None,
                    help="JSON file mapping model id -> one "
                         "representative request row (pre-compiles "
                         "padding buckets and publishes the artifact "
                         "manifest)")
    ap.add_argument("--tenancy", action="store_true",
                    help="multi-tenant tiering: register checkpoints "
                         "COLD (stat-only), demand-page on first "
                         "score, demote under the RAM budget")
    ap.add_argument("--tenancy-ram-budget-mb", type=float, default=None,
                    help="host-RAM budget for decoded model records "
                         "(default: TRANSMOGRIFAI_MODEL_RAM_BUDGET "
                         "env / unbounded)")
    ap.add_argument("--tenant-rate", type=float, default=None,
                    help="per-tenant admission tokens/s (0 disables "
                         "the fairness gate; default 200)")
    ap.add_argument("--tenancy-prewarm-top-k", type=int, default=0,
                    help="prewarm this many hottest models per daemon "
                         "tick (0 = no prewarm daemon)")
    args = ap.parse_args(argv)
    warm = None
    if args.warmup:
        with open(args.warmup) as fh:
            warm = json.load(fh)
    fleet_kwargs: dict = {
        "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
        "queue_capacity": args.queue_capacity,
        "min_bucket": (args.min_bucket if args.min_bucket is not None
                       else args.max_batch),
        "wire": args.wire}
    if args.shadow_tolerance is not None:
        fleet_kwargs["shadow_tolerance"] = args.shadow_tolerance
    if args.tenancy:
        from transmogrifai_tpu.tenancy import TenancyConfig
        budget = None
        if args.tenancy_ram_budget_mb is not None:
            budget = int(args.tenancy_ram_budget_mb * (1 << 20))
        rate = args.tenant_rate
        fleet_kwargs["tenancy"] = TenancyConfig(
            ram_budget_bytes=budget,
            rate_per_s=(None if rate == 0 else rate) if rate is not None
            else 200.0,
            prewarm_top_k=args.tenancy_prewarm_top_k)
    worker = ReplicaWorker(
        args.model_dir, args.state_dir, args.replica_id,
        port=args.port, host=args.host,
        heartbeat_interval_s=args.heartbeat_interval,
        use_artifacts=not args.no_artifacts,
        warmup_rows=warm, **fleet_kwargs)
    return worker.run()


if __name__ == "__main__":
    sys.exit(main())
