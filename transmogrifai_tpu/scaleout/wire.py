"""Scale-out wire protocol: heartbeat files + the admin control plane.

The contract between the three scale-out processes — supervisor, router
(both usually one control process) and N replica workers — kept
deliberately **stdlib-only** so a conformance stub (or an operator's
shell one-liner) can speak it without importing the framework:

- **heartbeats**: each replica atomically rewrites
  ``<state_dir>/replicas/<replica_id>.json`` every
  ``heartbeat_interval_s`` with its pid, bound HTTP port, lifecycle
  state, queue depths and serving counters. Writes go tmp-file +
  ``os.replace`` so the supervisor's poll NEVER reads a torn document;
  staleness (``ts`` older than the TTL) is the liveness signal that
  marks a replica down in the router before respawn.
- **admin control plane**: ``POST /admin/<action>`` on the replica's
  own HTTP port (``serving/http.py`` ``control_fn``), JSON in/out.
  Actions every worker implements: ``status`` (fleet snapshot +
  post-warmup compile counts), ``drain`` (quiesce: finish in-flight,
  report drained), ``swap`` (hot-swap one model behind the live
  endpoint; ``{"modelId", "version"|"path", "tolerance"?,
  "shadowRows"?}`` — ``shadowRows: 0`` skips the parity gate, the
  forced-rollback path), ``quit`` (graceful exit).

Replica lifecycle states (the ``state`` heartbeat field):
``starting -> ready -> draining -> stopped`` (+ ``swapping`` while an
admin swap is in flight). The router routes only to ``ready``.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import threading
import time
from typing import Optional

__all__ = ["HEARTBEAT_DIRNAME", "ReplicaStates", "heartbeat_path",
           "write_heartbeat", "read_heartbeats", "is_fresh",
           "admin_call", "AdminError", "atomic_write_json"]

#: subdirectory of the scale-out state dir holding one heartbeat file
#: per replica
HEARTBEAT_DIRNAME = "replicas"


class ReplicaStates:
    STARTING = "starting"
    READY = "ready"
    SWAPPING = "swapping"
    DRAINING = "draining"
    STOPPED = "stopped"


class AdminError(RuntimeError):
    """An admin call failed. ``status`` carries the HTTP code (0 for
    transport errors) and ``doc`` the decoded error body when one came
    back — 409 means a shadow-gate rejection (see serving/http.py).
    ``timeout`` is True when the failure was a DEADLINE — connect
    timeout, per-read socket timeout, or the call's overall deadline
    (a black-holed replica trickling bytes forever): the supervisor
    treats a timed-out replica as unhealthy, not the call as flaky."""

    def __init__(self, msg: str, status: int = 0,
                 doc: Optional[dict] = None, timeout: bool = False):
        super().__init__(msg)
        self.status = int(status)
        self.doc = doc or {}
        self.timeout = bool(timeout)


def atomic_write_json(doc: dict, path: str) -> None:
    """tmp-file + rename: a concurrent reader sees old or new, never a
    torn write. (Standalone twin of ``utils.durable.atomic_json_dump``
    so the stdlib-only stub worker can heartbeat without importing the
    framework.)"""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)


def heartbeat_path(state_dir: str, replica_id: str) -> str:
    return os.path.join(state_dir, HEARTBEAT_DIRNAME,
                        f"{replica_id}.json")


def write_heartbeat(state_dir: str, doc: dict) -> str:
    """Atomically publish one replica's heartbeat. ``doc`` must carry
    ``replicaId``; ``ts`` (epoch seconds) is stamped here so freshness
    is measured against the WRITE, not whenever the caller built the
    document."""
    replica_id = doc["replicaId"]
    path = heartbeat_path(state_dir, replica_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = dict(doc)
    doc["ts"] = time.time()
    atomic_write_json(doc, path)
    return path


def read_heartbeats(state_dir: str) -> dict:
    """replica_id -> heartbeat doc for every readable heartbeat file.
    Unreadable/corrupt files are skipped (atomic writes make that a
    transient race at worst, e.g. a replica deleted its own file on
    clean exit between listdir and open)."""
    hb_dir = os.path.join(state_dir, HEARTBEAT_DIRNAME)
    out: dict = {}
    try:
        names = os.listdir(hb_dir)
    except FileNotFoundError:
        return out
    for name in sorted(names):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(hb_dir, name)) as fh:
                doc = json.load(fh)
            rid = doc.get("replicaId")
            if rid:
                out[str(rid)] = doc
        except (OSError, ValueError):
            continue
    return out


def clear_heartbeat(state_dir: str, replica_id: str) -> None:
    """Remove a replica's heartbeat file (clean exit / forgotten
    replica) — best-effort."""
    try:
        os.remove(heartbeat_path(state_dir, replica_id))
    except OSError:
        pass


def is_fresh(doc: dict, ttl_s: float,
             now: Optional[float] = None) -> bool:
    """Liveness: the heartbeat's ``ts`` is within ``ttl_s`` of now."""
    ts = doc.get("ts")
    if not isinstance(ts, (int, float)):
        return False
    return (time.time() if now is None else now) - float(ts) <= ttl_s


# Per-thread keep-alive pool for the admin control plane. Supervisors
# poll ``status`` on every replica every tick — a fresh TCP connect per
# poll was the dominant control-plane cost (and, under SYN-flood-y
# chaos drills, a ladder of TIME_WAIT sockets). Thread-local because
# http.client connections are not thread-safe and the supervisor,
# router and tests all call in from their own threads.
_LOCAL = threading.local()


def _pooled_conn(host: str, port: int,
                 timeout_s: float) -> http.client.HTTPConnection:
    pool = getattr(_LOCAL, "admin_pool", None)
    if pool is None:
        pool = _LOCAL.admin_pool = {}
    conn = pool.get((host, port))
    if conn is None:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        pool[(host, port)] = conn
    else:
        conn.timeout = timeout_s
        if conn.sock is not None:
            conn.sock.settimeout(timeout_s)
    return conn


def _drop_conn(host: str, port: int) -> None:
    pool = getattr(_LOCAL, "admin_pool", None)
    conn = pool.pop((host, port), None) if pool else None
    if conn is not None:
        try:
            conn.close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


def _admin_once(conn: http.client.HTTPConnection, host: str, port: int,
                action: str, body: str) -> dict:
    conn.request("POST", f"/admin/{action}", body,
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()  # drain fully so the connection stays reusable
    try:
        doc = json.loads(raw) if raw else {}
    except ValueError:
        doc = {"raw": raw.decode(errors="replace")[:300]}
    if resp.status != 200:
        # an HTTP-level error is a *complete* exchange — the keep-alive
        # connection is still good, do NOT rebuild it
        raise AdminError(
            f"admin {action!r} on {host}:{port} -> {resp.status}: "
            f"{doc.get('error', doc)}", status=resp.status, doc=doc)
    return doc


def _is_timeout(e: BaseException) -> bool:
    return isinstance(e, (socket.timeout, TimeoutError))


class _Watchdog:
    """Overall-deadline enforcement for one admin exchange: a timer
    that hard-closes the connection's socket at the deadline, so a
    black-holed replica trickling one byte per socket-timeout window
    cannot hold the control plane past ``deadline_s``. ``fired`` tells
    the caller the resulting socket error was OUR deadline, not the
    network's."""

    def __init__(self, conn: http.client.HTTPConnection,
                 deadline_s: float):
        self.fired = False
        self._conn = conn
        self._timer = threading.Timer(deadline_s, self._expire)
        self._timer.daemon = True
        self._timer.start()

    def _expire(self) -> None:
        self.fired = True
        sock_ = self._conn.sock
        if sock_ is not None:
            # shutdown() BEFORE close(): closing an fd from another
            # thread does not wake a reader blocked in recv() — a
            # half-open peer trickling bytes would keep the exchange
            # alive past the deadline. shutdown delivers EOF to the
            # blocked reader immediately.
            try:
                sock_.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock_.close()
            except OSError:
                pass

    def cancel(self) -> None:
        self._timer.cancel()


def admin_call(port: int, action: str, payload: Optional[dict] = None,
               host: str = "127.0.0.1", timeout_s: float = 60.0,
               connect_timeout_s: Optional[float] = None,
               deadline_s: Optional[float] = None) -> dict:
    """One admin control-plane request; returns the decoded JSON reply
    or raises :class:`AdminError` (status 409 = shadow-gate rejection;
    ``timeout=True`` = a deadline fired, see below).

    Three independent bounds keep a misbehaving replica from hanging
    the supervisor's control plane:

    - ``connect_timeout_s`` (default ``min(timeout_s, 5)``): how long
      the TCP connect may take — a black-holed SYN fails fast instead
      of inheriting the full I/O timeout;
    - ``timeout_s``: the per-socket-operation bound (each recv);
    - ``deadline_s`` (default ``2 x timeout_s``): the OVERALL wall
      bound for the exchange — a replica trickling one byte per
      ``timeout_s`` window defeats per-recv timeouts, so a watchdog
      hard-closes the socket at the deadline.

    Connections are kept alive in a per-thread pool and reused across
    calls; only socket-level failures tear one down (with ONE silent
    retry on a fresh connection, since an idle keep-alive socket may
    have been closed server-side between calls). Error *statuses* ride
    the same connection — they don't cost a reconnect."""
    body = json.dumps(payload or {})
    if connect_timeout_s is None:
        connect_timeout_s = min(timeout_s, 5.0)
    if deadline_s is None:
        deadline_s = 2.0 * timeout_s

    def once(conn: http.client.HTTPConnection) -> dict:
        if conn.sock is None:
            # distinct (shorter) connect bound, then the I/O timeout
            conn.timeout = connect_timeout_s
            conn.connect()
            conn.sock.settimeout(timeout_s)
            conn.timeout = timeout_s
        dog = _Watchdog(conn, deadline_s)
        try:
            return _admin_once(conn, host, port, action, body)
        except Exception as e:
            if dog.fired:
                raise AdminError(
                    f"admin {action!r} on {host}:{port} exceeded the "
                    f"{deadline_s:g}s overall deadline",
                    timeout=True) from e
            raise
        finally:
            dog.cancel()

    conn = _pooled_conn(host, port, timeout_s)
    fresh = conn.sock is None
    try:
        return once(conn)
    except AdminError as ae:
        if ae.timeout:  # the watchdog half-closed the socket
            _drop_conn(host, port)
        raise
    except Exception as e:  # noqa: BLE001 — socket-level failure
        _drop_conn(host, port)
        if fresh:
            # connect itself failed — retrying immediately won't help
            raise AdminError(
                f"admin {action!r} on {host}:{port} failed: "
                f"{type(e).__name__}: {e}",
                timeout=_is_timeout(e)) from e
    # stale keep-alive socket: one retry on a brand-new connection
    conn = _pooled_conn(host, port, timeout_s)
    try:
        return once(conn)
    except AdminError as ae:
        if ae.timeout:
            _drop_conn(host, port)
        raise
    except Exception as e:  # noqa: BLE001 — transport failure, status 0
        _drop_conn(host, port)
        raise AdminError(
            f"admin {action!r} on {host}:{port} failed: "
            f"{type(e).__name__}: {e}",
            timeout=_is_timeout(e)) from e
