"""Feature-operation DSL: rich methods on FeatureLike.

Parity: reference ``core/src/main/scala/com/salesforce/op/dsl/*`` (11 files
of implicit Rich*Feature classes) — ``age + fare``, ``text.tokenize()``,
``city.pivot()``, ``features.transmogrify()``, ``label.sanity_check(vec)``
etc. Importing this module attaches the methods to FeatureLike (the Python
analog of the package-object implicits).
"""

from __future__ import annotations

from typing import Sequence

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["install", "transmogrify_features"]


def _math(op):
    def fn(self, other):
        from transmogrifai_tpu.ops.math import (
            BinaryMathTransformer, ScalarMathTransformer,
        )
        if isinstance(other, FeatureLike):
            return self.transform_with(BinaryMathTransformer(op=op), other)
        return self.transform_with(
            ScalarMathTransformer(op=op, scalar=float(other)))
    return fn


def _alias(self, name: str):
    from transmogrifai_tpu.ops.math import AliasTransformer
    return self.transform_with(AliasTransformer(name=name))


def _abs(self):
    from transmogrifai_tpu.ops.math import UnaryMathTransformer
    return self.transform_with(UnaryMathTransformer(op="abs"))


def _log(self):
    from transmogrifai_tpu.ops.math import UnaryMathTransformer
    return self.transform_with(UnaryMathTransformer(op="log"))

def _sqrt(self):
    from transmogrifai_tpu.ops.math import UnaryMathTransformer
    return self.transform_with(UnaryMathTransformer(op="sqrt"))


def _to_occur(self):
    from transmogrifai_tpu.ops.math import ToOccurTransformer
    return self.transform_with(ToOccurTransformer())


def _z_normalize(self):
    from transmogrifai_tpu.ops.math import OpScalarStandardScaler
    return self.transform_with(OpScalarStandardScaler())

def _fill_missing_with_mean(self):
    from transmogrifai_tpu.ops.math import FillMissingWithMean
    return self.transform_with(FillMissingWithMean())


def _tokenize(self, **kw):
    from transmogrifai_tpu.ops.text import TextTokenizer
    return self.transform_with(TextTokenizer(**kw))


def _detect_languages(self):
    from transmogrifai_tpu.ops.text import LangDetector
    return self.transform_with(LangDetector())


def _pivot(self, top_k: int = 20, min_support: int = 10, **kw):
    from transmogrifai_tpu.ops.vectorizers.onehot import OneHotVectorizer
    return self.transform_with(
        OneHotVectorizer(top_k=top_k, min_support=min_support, **kw))


def _vectorize(self, *others, **kw):
    """Type-default vectorization of this feature (+ ``others``, the
    reference Rich*Feature ``vectorize(others = ...)`` convention)."""
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    return transmogrify([self, *others], **kw)


def _smart_vectorize(self, **kw):
    from transmogrifai_tpu.ops.smart_text import SmartTextVectorizer
    return self.transform_with(SmartTextVectorizer(**kw))


def _sanity_check(self, features: FeatureLike, **kw):
    """label.sanity_check(feature_vector) -> cleaned vector."""
    from transmogrifai_tpu.preparators import SanityChecker
    return self.transform_with(SanityChecker(**kw), features)


def _combine(self, *others):
    from transmogrifai_tpu.ops.combiner import VectorsCombiner
    return self.transform_with(VectorsCombiner(), *others)


def _similarity(self, other, n: int = 3):
    from transmogrifai_tpu.ops.text import NGramSimilarity
    return self.transform_with(NGramSimilarity(n=n), other)


def _count_vectorize(self, **kw):
    from transmogrifai_tpu.ops.text_models import OpCountVectorizer
    return self.transform_with(OpCountVectorizer(**kw))


def _word2vec(self, **kw):
    from transmogrifai_tpu.ops.text_models import OpWord2Vec
    return self.transform_with(OpWord2Vec(**kw))


def _lda(self, **kw):
    from transmogrifai_tpu.ops.text_models import OpLDA
    return self.transform_with(OpLDA(**kw))


def _to_time_period(self, period="DayOfMonth"):
    from transmogrifai_tpu.ops.time_period import TimePeriodTransformer
    return self.transform_with(TimePeriodTransformer(period=period))


def _name_entity_tagger(self, **kw):
    from transmogrifai_tpu.ops.names import NameEntityRecognizer
    return self.transform_with(NameEntityRecognizer(**kw))


def _detect_human_names(self, **kw):
    from transmogrifai_tpu.ops.names import HumanNameDetector
    return self.transform_with(HumanNameDetector(**kw))


def _bucketize(self, splits, track_nulls: bool = True,
               track_invalid: bool = False, labels=None):
    from transmogrifai_tpu.ops.vectorizers.bucketizers import NumericBucketizer
    return self.transform_with(NumericBucketizer(
        splits=splits, track_nulls=track_nulls, track_invalid=track_invalid,
        labels=labels))


def _auto_bucketize(self, label, **kw):
    """feature.auto_bucketize(label) — label-aware decision-tree buckets."""
    from transmogrifai_tpu.ops.vectorizers.bucketizers import (
        DecisionTreeNumericBucketizer,
    )
    return label.transform_with(DecisionTreeNumericBucketizer(**kw), self)


def _to_percentile(self, buckets: int = 100):
    from transmogrifai_tpu.ops.vectorizers.bucketizers import (
        PercentileCalibrator,
    )
    return self.transform_with(PercentileCalibrator(
        expected_num_buckets=buckets))


def _index_string(self, no_filter: bool = True, **kw):
    from transmogrifai_tpu.ops.indexers import (
        OpStringIndexer, OpStringIndexerNoFilter,
    )
    stage = OpStringIndexerNoFilter(**kw) if no_filter else OpStringIndexer(**kw)
    return self.transform_with(stage)


# -- RichTextFeature surface (email/url/phone/base64) -----------------------

def _email_domain(self):
    from transmogrifai_tpu.ops.parsers import EmailToPickList
    return self.transform_with(EmailToPickList())


def _is_valid_email(self):
    from transmogrifai_tpu.ops.parsers import ValidEmailTransformer
    return self.transform_with(ValidEmailTransformer())


def _url_domain(self):
    from transmogrifai_tpu.ops.parsers import UrlToPickList
    return self.transform_with(UrlToPickList())


def _is_valid_url(self):
    from transmogrifai_tpu.ops.parsers import ValidUrlTransformer
    return self.transform_with(ValidUrlTransformer())


def _parse_phone(self, region=None, **kw):
    from transmogrifai_tpu.ops.parsers import (
        ParsePhoneDefaultCountry, ParsePhoneNumber,
    )
    if isinstance(region, FeatureLike):
        return self.transform_with(ParsePhoneNumber(**kw), region)
    if region is not None:
        kw.setdefault("default_region", region)
    return self.transform_with(ParsePhoneDefaultCountry(**kw))


def _is_valid_phone(self, region=None, **kw):
    from transmogrifai_tpu.ops.parsers import (
        IsValidPhoneNumber, PhoneNumberParser,
    )
    if isinstance(region, FeatureLike):
        return self.transform_with(IsValidPhoneNumber(**kw), region)
    if region is not None:
        kw.setdefault("default_region", region)
    return self.transform_with(PhoneNumberParser(**kw))


def _mime_type(self):
    from transmogrifai_tpu.ops.parsers import MimeTypeDetector
    return self.transform_with(MimeTypeDetector())


def _text_len(self, *others):
    from transmogrifai_tpu.ops.text import TextLenTransformer
    return self.transform_with(TextLenTransformer(), *others)


def _remove_stopwords(self, **kw):
    from transmogrifai_tpu.ops.text import OpStopWordsRemover
    return self.transform_with(OpStopWordsRemover(**kw))


def _ngram(self, n: int = 2):
    from transmogrifai_tpu.ops.text import OpNGram
    return self.transform_with(OpNGram(n=n))


# -- RichDateFeature surface ------------------------------------------------

def _to_unit_circle(self, period="HourOfDay"):
    from transmogrifai_tpu.ops.vectorizers.dates import (
        DateToUnitCircleVectorizer,
    )
    return self.transform_with(DateToUnitCircleVectorizer(time_period=period))


def _to_time_period_list(self, period="DayOfMonth"):
    from transmogrifai_tpu.ops.time_period import TimePeriodListTransformer
    return self.transform_with(TimePeriodListTransformer(period=period))


# -- RichMapFeature surface -------------------------------------------------

def _pivot_map(self, **kw):
    from transmogrifai_tpu.ops.vectorizers.maps import TextMapPivotVectorizer
    return self.transform_with(TextMapPivotVectorizer(**kw))


def _smart_vectorize_map(self, **kw):
    from transmogrifai_tpu.ops.vectorizers.maps import SmartTextMapVectorizer
    return self.transform_with(SmartTextMapVectorizer(**kw))


def _map_lengths(self, **kw):
    from transmogrifai_tpu.ops.vectorizers.maps import TextMapLenEstimator
    return self.transform_with(TextMapLenEstimator(**kw))


def _map_null_indicators(self, **kw):
    from transmogrifai_tpu.ops.vectorizers.maps import TextMapNullEstimator
    return self.transform_with(TextMapNullEstimator(**kw))


def _to_time_period_map(self, period="DayOfMonth"):
    from transmogrifai_tpu.ops.time_period import TimePeriodMapTransformer
    return self.transform_with(TimePeriodMapTransformer(period=period))


def _is_valid_phone_map(self, **kw):
    from transmogrifai_tpu.ops.parsers import IsValidPhoneMapDefaultCountry
    return self.transform_with(IsValidPhoneMapDefaultCountry(**kw))


def _filter_map_keys(self, allow_list=(), block_list=()):
    from transmogrifai_tpu.ops.vectorizers.maps import FilterMapKeys
    return self.transform_with(FilterMapKeys(allow_list=allow_list,
                                             block_list=block_list))


def _mime_type_map(self):
    from transmogrifai_tpu.ops.vectorizers.maps import Base64MapMimeDetector
    return self.transform_with(Base64MapMimeDetector())


def _to_unit_circle_map(self, period="HourOfDay"):
    from transmogrifai_tpu.ops.vectorizers.maps import (
        DateMapToUnitCircleVectorizer,
    )
    return self.transform_with(DateMapToUnitCircleVectorizer(
        time_period=period))


def _auto_bucketize_map(self, label, **kw):
    from transmogrifai_tpu.ops.vectorizers.bucketizers import (
        DecisionTreeNumericMapBucketizer,
    )
    return label.transform_with(DecisionTreeNumericMapBucketizer(**kw), self)


# -- Prediction accessors (reference Prediction implicit extractors) --------

def _pred_value(self):
    from transmogrifai_tpu.ops.combiner import PredictionToReal
    return self.transform_with(PredictionToReal())


def _pred_probability(self):
    from transmogrifai_tpu.ops.combiner import PredictionProbabilityVector
    return self.transform_with(PredictionProbabilityVector())


def _pred_raw(self):
    from transmogrifai_tpu.ops.combiner import PredictionRawVector
    return self.transform_with(PredictionRawVector())


def _tupled(self):
    """prediction.tupled() -> (RealNN value, raw OPVector, prob OPVector)
    (reference RichMapFeature.tupled)."""
    return _pred_value(self), _pred_raw(self), _pred_probability(self)


# -- scaling / calibration / prediction -------------------------------------

def _scale(self, slope: float = 1.0, intercept: float = 0.0):
    from transmogrifai_tpu.ops.math import ScalerTransformer
    return self.transform_with(ScalerTransformer(slope=slope,
                                                 intercept=intercept))


def _descale(self, slope: float = 1.0, intercept: float = 0.0):
    from transmogrifai_tpu.ops.math import DescalerTransformer
    return self.transform_with(DescalerTransformer(slope=slope,
                                                   intercept=intercept))


def _calibrate(self, prediction, **kw):
    """label.calibrate(prediction) -> isotonic-calibrated prediction."""
    from transmogrifai_tpu.models.extras import IsotonicRegressionCalibrator
    return self.transform_with(IsotonicRegressionCalibrator(**kw), prediction)


def _combine_predictions(self, pred1, pred2, **kw):
    """label.combine_predictions(p1, p2) -> metric-weighted ensemble."""
    from transmogrifai_tpu.selector.extras import SelectedModelCombiner
    return self.transform_with(SelectedModelCombiner(**kw), pred1, pred2)


def _record_insights(self, features, **kw):
    """prediction.record_insights(feature_vector) -> per-record TextMap."""
    from transmogrifai_tpu.insights import RecordInsightsCorr
    return self.transform_with(RecordInsightsCorr(**kw), features)


def _map(self, fn, out_type=None, operation_name="map"):
    """Arbitrary row-function transform (reference RichFeature ``map`` via
    UnaryLambdaTransformer); ``fn`` must be importable to serialize."""
    from transmogrifai_tpu.stages.base import LambdaTransformer
    return self.transform_with(LambdaTransformer(
        fn, in_types=(self.ftype,), out_type=out_type or self.ftype,
        operation_name=operation_name))


def _exists(self, predicate):
    from transmogrifai_tpu.ops.math import ExistsTransformer
    return self.transform_with(ExistsTransformer(predicate=predicate))


def _filter_values(self, predicate, default=None):
    from transmogrifai_tpu.ops.math import FilterValueTransformer
    return self.transform_with(
        FilterValueTransformer(predicate=predicate, default=default))


def _replace_with(self, old, new):
    from transmogrifai_tpu.ops.math import ReplaceTransformer
    return self.transform_with(ReplaceTransformer(old=old, new=new))


def _is_substring_of(self, full, to_lowercase: bool = True):
    from transmogrifai_tpu.ops.math import SubstringTransformer
    return self.transform_with(
        SubstringTransformer(to_lowercase=to_lowercase), full)


def _email_prefix(self):
    from transmogrifai_tpu.ops.parsers import EmailPrefixTransformer
    return self.transform_with(EmailPrefixTransformer())


def _url_protocol(self):
    from transmogrifai_tpu.ops.parsers import UrlProtocolTransformer
    return self.transform_with(UrlProtocolTransformer())


def _to_multi_pick_list(self):
    from transmogrifai_tpu.ops.text import TextToMultiPickList
    return self.transform_with(TextToMultiPickList())


def _tokenize_regex(self, pattern, group: int = -1,
                    min_token_length: int = 1, lowercase: bool = True):
    from transmogrifai_tpu.ops.text import RegexTokenizer
    return self.transform_with(RegexTokenizer(
        pattern=pattern, group=group, min_token_length=min_token_length,
        lowercase=lowercase))


def _tf(self, num_features: int = 512, binary_freq: bool = False):
    from transmogrifai_tpu.ops.vector_ops import OpHashingTF
    return self.transform_with(OpHashingTF(
        num_features=num_features, binary_freq=binary_freq))


def _idf(self, min_doc_freq: int = 0):
    from transmogrifai_tpu.ops.vector_ops import OpIDF
    return self.transform_with(OpIDF(min_doc_freq=min_doc_freq))


def _tfidf(self, num_features: int = 512, binary_freq: bool = False,
           min_doc_freq: int = 0):
    return _idf(_tf(self, num_features, binary_freq), min_doc_freq)


def _jaccard_similarity(self, other):
    from transmogrifai_tpu.ops.text import SetJaccardSimilarity
    return self.transform_with(SetJaccardSimilarity(), other)


def _drop_indices_by(self, match_fn):
    from transmogrifai_tpu.ops.vector_ops import DropIndicesByTransformer
    return self.transform_with(DropIndicesByTransformer(match_fn=match_fn))


def _filter_min_variance(self, min_variance: float = 1e-5):
    from transmogrifai_tpu.ops.vector_ops import MinVarianceFilter
    return self.transform_with(MinVarianceFilter(min_variance=min_variance))


def transmogrify_features(features: Sequence[FeatureLike], **kw) -> FeatureLike:
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    return transmogrify(list(features), **kw)


def install() -> None:
    """Attach the DSL methods (idempotent)."""
    F = FeatureLike
    F.__add__ = _math("+")
    F.__sub__ = _math("-")
    F.__mul__ = _math("*")
    F.__truediv__ = _math("/")
    F.alias = _alias
    F.abs = _abs
    F.log = _log
    F.sqrt = _sqrt
    F.to_occur = _to_occur
    F.z_normalize = _z_normalize
    F.fill_missing_with_mean = _fill_missing_with_mean
    F.tokenize = _tokenize
    F.detect_languages = _detect_languages
    F.pivot = _pivot
    F.vectorize = _vectorize
    F.smart_vectorize = _smart_vectorize
    F.sanity_check = _sanity_check
    F.combine = _combine
    F.similarity = _similarity
    F.count_vectorize = _count_vectorize
    F.word2vec = _word2vec
    F.lda = _lda
    F.to_time_period = _to_time_period
    F.name_entity_tagger = _name_entity_tagger
    F.detect_human_names = _detect_human_names
    F.bucketize = _bucketize
    F.auto_bucketize = _auto_bucketize
    F.to_percentile = _to_percentile
    F.index_string = _index_string
    # RichTextFeature
    F.email_domain = _email_domain
    F.is_valid_email = _is_valid_email
    F.url_domain = _url_domain
    F.is_valid_url = _is_valid_url
    F.parse_phone = _parse_phone
    F.is_valid_phone = _is_valid_phone
    F.mime_type = _mime_type
    F.text_len = _text_len
    F.remove_stopwords = _remove_stopwords
    F.ngram = _ngram
    # RichDateFeature
    F.to_unit_circle = _to_unit_circle
    F.to_time_period_list = _to_time_period_list
    # RichMapFeature
    F.pivot_map = _pivot_map
    F.smart_vectorize_map = _smart_vectorize_map
    F.map_lengths = _map_lengths
    F.map_null_indicators = _map_null_indicators
    F.to_time_period_map = _to_time_period_map
    F.is_valid_phone_map = _is_valid_phone_map
    F.filter_map_keys = _filter_map_keys
    F.mime_type_map = _mime_type_map
    F.to_unit_circle_map = _to_unit_circle_map
    F.auto_bucketize_map = _auto_bucketize_map
    # Prediction accessors
    F.pred_value = _pred_value
    F.pred_probability = _pred_probability
    F.pred_raw = _pred_raw
    F.tupled = _tupled
    # RichFeature generic ops
    F.map = _map
    F.exists = _exists
    F.filter_values = _filter_values
    F.replace_with = _replace_with
    # text surface
    F.is_substring_of = _is_substring_of
    F.email_prefix = _email_prefix
    F.url_protocol = _url_protocol
    F.to_multi_pick_list = _to_multi_pick_list
    F.tokenize_regex = _tokenize_regex
    # RichSetFeature
    F.jaccard_similarity = _jaccard_similarity
    # RichListFeature / RichVectorFeature
    F.tf = _tf
    F.idf = _idf
    F.tfidf = _tfidf
    F.drop_indices_by = _drop_indices_by
    F.filter_min_variance = _filter_min_variance
    # scaling / calibration / prediction
    F.scale = _scale
    F.descale = _descale
    F.calibrate = _calibrate
    F.combine_predictions = _combine_predictions
    F.record_insights = _record_insights


install()
