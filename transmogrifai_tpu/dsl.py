"""Feature-operation DSL: rich methods on FeatureLike.

Parity: reference ``core/src/main/scala/com/salesforce/op/dsl/*`` (11 files
of implicit Rich*Feature classes) — ``age + fare``, ``text.tokenize()``,
``city.pivot()``, ``features.transmogrify()``, ``label.sanity_check(vec)``
etc. Importing this module attaches the methods to FeatureLike (the Python
analog of the package-object implicits).
"""

from __future__ import annotations

from typing import Sequence

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["install", "transmogrify_features"]


def _math(op):
    def fn(self, other):
        from transmogrifai_tpu.ops.math import (
            BinaryMathTransformer, ScalarMathTransformer,
        )
        if isinstance(other, FeatureLike):
            return self.transform_with(BinaryMathTransformer(op=op), other)
        return self.transform_with(
            ScalarMathTransformer(op=op, scalar=float(other)))
    return fn


def _alias(self, name: str):
    from transmogrifai_tpu.ops.math import AliasTransformer
    return self.transform_with(AliasTransformer(name=name))


def _abs(self):
    from transmogrifai_tpu.ops.math import UnaryMathTransformer
    return self.transform_with(UnaryMathTransformer(op="abs"))


def _log(self):
    from transmogrifai_tpu.ops.math import UnaryMathTransformer
    return self.transform_with(UnaryMathTransformer(op="log"))

def _sqrt(self):
    from transmogrifai_tpu.ops.math import UnaryMathTransformer
    return self.transform_with(UnaryMathTransformer(op="sqrt"))


def _to_occur(self):
    from transmogrifai_tpu.ops.math import ToOccurTransformer
    return self.transform_with(ToOccurTransformer())


def _z_normalize(self):
    from transmogrifai_tpu.ops.math import OpScalarStandardScaler
    return self.transform_with(OpScalarStandardScaler())

def _fill_missing_with_mean(self):
    from transmogrifai_tpu.ops.math import FillMissingWithMean
    return self.transform_with(FillMissingWithMean())


def _tokenize(self, **kw):
    from transmogrifai_tpu.ops.text import TextTokenizer
    return self.transform_with(TextTokenizer(**kw))


def _detect_languages(self):
    from transmogrifai_tpu.ops.text import LangDetector
    return self.transform_with(LangDetector())


def _pivot(self, top_k: int = 20, min_support: int = 10, **kw):
    from transmogrifai_tpu.ops.vectorizers.onehot import OneHotVectorizer
    return self.transform_with(
        OneHotVectorizer(top_k=top_k, min_support=min_support, **kw))


def _vectorize(self, **kw):
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    return transmogrify([self], **kw)


def _smart_vectorize(self, **kw):
    from transmogrifai_tpu.ops.smart_text import SmartTextVectorizer
    return self.transform_with(SmartTextVectorizer(**kw))


def _sanity_check(self, features: FeatureLike, **kw):
    """label.sanity_check(feature_vector) -> cleaned vector."""
    from transmogrifai_tpu.preparators import SanityChecker
    return self.transform_with(SanityChecker(**kw), features)


def _combine(self, *others):
    from transmogrifai_tpu.ops.combiner import VectorsCombiner
    return self.transform_with(VectorsCombiner(), *others)


def _similarity(self, other, n: int = 3):
    from transmogrifai_tpu.ops.text import NGramSimilarity
    return self.transform_with(NGramSimilarity(n=n), other)


def _count_vectorize(self, **kw):
    from transmogrifai_tpu.ops.text_models import OpCountVectorizer
    return self.transform_with(OpCountVectorizer(**kw))


def _word2vec(self, **kw):
    from transmogrifai_tpu.ops.text_models import OpWord2Vec
    return self.transform_with(OpWord2Vec(**kw))


def _lda(self, **kw):
    from transmogrifai_tpu.ops.text_models import OpLDA
    return self.transform_with(OpLDA(**kw))


def _to_time_period(self, period="DayOfMonth"):
    from transmogrifai_tpu.ops.time_period import TimePeriodTransformer
    return self.transform_with(TimePeriodTransformer(period=period))


def _name_entity_tagger(self, **kw):
    from transmogrifai_tpu.ops.names import NameEntityRecognizer
    return self.transform_with(NameEntityRecognizer(**kw))


def _detect_human_names(self, **kw):
    from transmogrifai_tpu.ops.names import HumanNameDetector
    return self.transform_with(HumanNameDetector(**kw))


def _bucketize(self, splits, track_nulls: bool = True,
               track_invalid: bool = False, labels=None):
    from transmogrifai_tpu.ops.vectorizers.bucketizers import NumericBucketizer
    return self.transform_with(NumericBucketizer(
        splits=splits, track_nulls=track_nulls, track_invalid=track_invalid,
        labels=labels))


def _auto_bucketize(self, label, **kw):
    """feature.auto_bucketize(label) — label-aware decision-tree buckets."""
    from transmogrifai_tpu.ops.vectorizers.bucketizers import (
        DecisionTreeNumericBucketizer,
    )
    return label.transform_with(DecisionTreeNumericBucketizer(**kw), self)


def _to_percentile(self, buckets: int = 100):
    from transmogrifai_tpu.ops.vectorizers.bucketizers import (
        PercentileCalibrator,
    )
    return self.transform_with(PercentileCalibrator(
        expected_num_buckets=buckets))


def _index_string(self, no_filter: bool = True, **kw):
    from transmogrifai_tpu.ops.indexers import (
        OpStringIndexer, OpStringIndexerNoFilter,
    )
    stage = OpStringIndexerNoFilter(**kw) if no_filter else OpStringIndexer(**kw)
    return self.transform_with(stage)


def transmogrify_features(features: Sequence[FeatureLike], **kw) -> FeatureLike:
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    return transmogrify(list(features), **kw)


def install() -> None:
    """Attach the DSL methods (idempotent)."""
    F = FeatureLike
    F.__add__ = _math("+")
    F.__sub__ = _math("-")
    F.__mul__ = _math("*")
    F.__truediv__ = _math("/")
    F.alias = _alias
    F.abs = _abs
    F.log = _log
    F.sqrt = _sqrt
    F.to_occur = _to_occur
    F.z_normalize = _z_normalize
    F.fill_missing_with_mean = _fill_missing_with_mean
    F.tokenize = _tokenize
    F.detect_languages = _detect_languages
    F.pivot = _pivot
    F.vectorize = _vectorize
    F.smart_vectorize = _smart_vectorize
    F.sanity_check = _sanity_check
    F.combine = _combine
    F.similarity = _similarity
    F.count_vectorize = _count_vectorize
    F.word2vec = _word2vec
    F.lda = _lda
    F.to_time_period = _to_time_period
    F.name_entity_tagger = _name_entity_tagger
    F.detect_human_names = _detect_human_names
    F.bucketize = _bucketize
    F.auto_bucketize = _auto_bucketize
    F.to_percentile = _to_percentile
    F.index_string = _index_string


install()
