"""DAG compilation and layer-fused execution.

Parity: reference ``core/.../utils/stages/FitStagesUtil.scala:96-369`` —
``computeDAG`` levels stages by max distance-to-result; ``fitAndTransformDAG``
folds over layers fitting estimators then bulk-applying transformers;
``applyOpTransformations`` fuses all row-level transformers of a layer into
one pass.

TPU-first: the per-layer fusion target is a single jitted XLA program over
device columns (params passed as a pytree so recompilation is shape-keyed
only); host transformers run eagerly before it. Compiled programs are cached
per (layer stage uids) on the executor, so repeated scoring reuses them.

Round 14 extends fusion past the single layer: a maximal run of consecutive
ALL-device DAG levels compiles as ONE jitted program
(``fuse_dag_program``) — intermediate columns live only inside the program
(XLA register/VMEM residency, no HBM round-trip between levels), and when
every level is fusable the whole ingest->features pipeline feeding the
ModelSelector is a single device dispatch. Gated by
``TRANSMOGRIFAI_FE_FUSED=1|0`` (default on); with the gate off the
pre-fusion per-layer path runs byte-for-byte (counter-asserted in tests and
the committed ``INGEST_FE_FUSION.json``). An OOM inside a fused segment
takes the resource ladder's ``ingest.fuse`` rung: the segment re-applies
stage-by-stage (peak memory ~ one stage's block, not the whole segment's
intermediates) and the run completes.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

_PROFILE = os.environ.get("TRANSMOGRIFAI_PROFILE") == "1"


def _plog(msg: str, t0: float) -> None:
    if _PROFILE:
        print(f"[profile] {msg}: {time.time() - t0:.2f}s", file=sys.stderr)

from transmogrifai_tpu.features.feature import FeatureLike
from transmogrifai_tpu.pipeline_data import PipelineData
from transmogrifai_tpu.stages.base import (
    Estimator, PipelineStage, Transformer,
)
from transmogrifai_tpu.utils.tracing import device_scope, span

__all__ = ["compute_dag", "cut_dag", "CutDag", "DagExecutor", "Dag",
           "fuse_layer_program", "fuse_dag_program", "fe_fused_enabled",
           "fusable_segments"]

Dag = list  # list[list[PipelineStage]], execution order


def fe_fused_enabled() -> bool:
    """Master gate for multi-layer FE fusion (``TRANSMOGRIFAI_FE_FUSED``,
    default on). Off = the pre-round-14 per-layer execution path,
    byte-for-byte."""
    return os.environ.get("TRANSMOGRIFAI_FE_FUSED", "1") != "0"


def _layer_fusable(layer) -> bool:
    """A DAG level joins a fused segment when every stage is a device
    transformer (host/string stages force eager materialization)."""
    return bool(layer) and all(
        isinstance(s, Transformer) and s.is_device for s in layer)


def fusable_segments(dag: Dag):
    """Partition a fitted DAG into execution segments: ``("fused",
    [layer, ...])`` for each maximal run of consecutive all-device levels,
    ``("layer", layer)`` for everything else. Segment order preserves DAG
    order, so replaying segments is exactly replaying the DAG."""
    run: list = []
    for layer in dag:
        if _layer_fusable(layer):
            run.append(layer)
            continue
        if run:
            yield ("fused", run)
            run = []
        yield ("layer", layer)
    if run:
        yield ("fused", run)


def compute_dag(result_features: Sequence[FeatureLike]) -> Dag:
    """Level the ancestor stages of the result features by max distance to
    any result; farthest layer executes first. Raw feature generators are
    excluded (they run at ingest, inside the readers)."""
    dist: dict[PipelineStage, int] = {}
    for f in result_features:
        for stage, d in f.parent_stages().items():
            if stage.is_raw_generator:
                continue
            if stage not in dist or dist[stage] < d:
                dist[stage] = d
    if not dist:
        return []
    _check_distinct_uids(dist)
    max_d = max(dist.values())
    layers: list[list[PipelineStage]] = [[] for _ in range(max_d + 1)]
    for stage, d in dist.items():
        layers[max_d - d].append(stage)
    # stable order within a layer: by uid for determinism
    for layer in layers:
        layer.sort(key=lambda s: s.uid)
    return [l for l in layers if l]


class CutDag:
    """The DAG cut around the ModelSelector for leakage-free workflow CV.

    Parity: reference ``FitStagesUtil.cutDAG`` (``FitStagesUtil.scala:
    302-355``) — splits the workflow DAG into:
      - ``before``: stages safe to fit once on the full training data
      - ``during``: label-dependent feature stages (and everything at or
        after them on the selector's ancestor path) that must be refit
        inside every CV fold to avoid leaking label information
      - ``after``: stages downstream of the selector or of any during stage
    """

    def __init__(self, selector, before: Dag, during: Dag, after: Dag):
        self.selector = selector
        self.before = before
        self.during = during
        self.after = after


def cut_dag(result_features: Sequence[FeatureLike]) -> CutDag:
    from transmogrifai_tpu.selector.model_selector import ModelSelector

    dag = compute_dag(result_features)
    selectors = [s for layer in dag for s in layer
                 if isinstance(s, ModelSelector)]
    if not selectors:
        return CutDag(None, dag, [], [])
    if len(selectors) > 1:
        raise ValueError(
            f"Workflow can contain at most 1 ModelSelector, found "
            f"{len(selectors)}: {selectors}")
    ms = selectors[0]

    # the selector's ancestor DAG, least-deep layer last (selector excluded)
    ms_dag = compute_dag([ms.get_output()])
    ms_dag = [[s for s in layer if s is not ms] for layer in ms_dag]
    ms_dag = [l for l in ms_dag if l]

    # first layer containing a label-dependent stage (inputs mix response
    # and predictors): everything from there on refits inside each fold
    def label_dependent(stage) -> bool:
        ins = stage.input_features
        return (any(f.is_response for f in ins)
                and any(not f.is_response for f in ins))

    first = next((i for i, layer in enumerate(ms_dag)
                  if any(label_dependent(s) for s in layer)), None)
    during_layers = ms_dag[first:] if first is not None else []
    during_set = {s for layer in during_layers for s in layer}

    def ancestors(stage) -> set:
        out: set = set()
        for f in stage.input_features:
            out.update(f.parent_stages().keys())
        return out

    before: Dag = []
    after: Dag = []
    for layer in dag:
        b_layer, a_layer = [], []
        for s in layer:
            if s is ms or s in during_set:
                continue
            anc = ancestors(s)
            if ms in anc or (anc & during_set):
                a_layer.append(s)
            else:
                b_layer.append(s)
        if b_layer:
            before.append(b_layer)
        if a_layer:
            after.append(a_layer)
    return CutDag(ms, before, during_layers, after)


def _check_distinct_uids(dist) -> None:
    seen: dict[str, PipelineStage] = {}
    for stage in dist:
        other = seen.get(stage.uid)
        if other is not None and other is not stage:
            raise ValueError(
                f"Duplicate stage uid {stage.uid} for distinct stage objects "
                "(reference checkDistinctUIDs)")
        seen[stage.uid] = stage


class DagExecutor:
    """Fits/applies a leveled DAG over PipelineData with per-layer fusion
    (and, round 14, cross-layer fusion of all-device level runs)."""

    def __init__(self):
        self._fused_cache: dict[tuple[str, ...], Any] = {}
        #: cross-layer fused programs, keyed by the segment's stage uids
        self._fused_dag_cache: dict[tuple[str, ...], Any] = {}

    # -- fit -----------------------------------------------------------------
    def fit_transform(self, data: PipelineData, dag: Dag
                      ) -> tuple[PipelineData, Dag]:
        """Fold over layers: fit estimators, then apply the whole layer.
        Returns transformed data + the fitted DAG (estimators replaced by
        their models). With FE fusion on, consecutive estimator-free
        all-device layers DEFER application and flush as one fused device
        program at the next materialization point (an estimator fit, a
        host layer, or the end of the DAG) — the whole-pipeline fusion the
        fitted-DAG replay path gets unconditionally."""
        fuse = fe_fused_enabled()
        fitted_dag: Dag = []
        pending: list = []  # deferred all-device fitted layers

        def flush(d: PipelineData) -> PipelineData:
            if not pending:
                return d
            t0 = time.time()
            d = self.apply_fused(d, list(pending))
            _plog(f"apply fused segment ({len(pending)} layers)", t0)
            pending.clear()
            return d

        for layer in dag:
            has_estimator = any(isinstance(s, Estimator) for s in layer)
            if fuse and not has_estimator and _layer_fusable(layer):
                pending.append(layer)
                fitted_dag.append(list(layer))
                continue
            data = flush(data)
            fitted_layer: list[Transformer] = []
            for stage in layer:
                if isinstance(stage, Estimator):
                    t0 = time.time()
                    with span("stage.fit", hbm=True, stage_uid=stage.uid,
                              stage_cls=type(stage).__name__,
                              op=stage.operation_name, phase="fit"):
                        fitted_layer.append(stage.fit(data))
                    _plog(f"fit {stage.operation_name}", t0)
                elif isinstance(stage, Transformer):
                    fitted_layer.append(stage)
                else:
                    raise TypeError(f"Cannot execute stage {stage!r}")
            t0 = time.time()
            if fuse and _layer_fusable(fitted_layer):
                data = self.apply_fused(data, [fitted_layer])
            else:
                data = self.apply_layer(data, fitted_layer)
            _plog(f"apply layer [{', '.join(t.operation_name for t in fitted_layer)}]",
                  t0)
            fitted_dag.append(fitted_layer)
        data = flush(data)
        return data, fitted_dag

    # -- transform -----------------------------------------------------------
    def transform(self, data: PipelineData, dag: Dag) -> PipelineData:
        if not fe_fused_enabled():
            # the pre-fusion path, byte-for-byte (counter-asserted: no
            # fused segment programs run with the gate off)
            for layer in dag:
                data = self.apply_layer(data, layer)
            return data
        for kind, seg in fusable_segments(dag):
            if kind == "fused":
                data = self.apply_fused(data, seg)
            else:
                data = self.apply_layer(data, seg)
        return data

    def apply_layer(self, data: PipelineData,
                    transformers: Sequence[Transformer]) -> PipelineData:
        host_ts = [t for t in transformers if not t.is_device]
        dev_ts = [t for t in transformers if t.is_device]
        if host_ts:
            # host transformers run eagerly one at a time — each gets its
            # own stage span (the "which vectorizer is slow" answer)
            new_host = {}
            for t in host_ts:
                with span("stage.transform", hbm=True, stage_uid=t.uid,
                          stage_cls=type(t).__name__,
                          op=t.operation_name, phase="transform"):
                    new_host[t.get_output().name] = t.output_column(data)
            data = data.with_host_cols(new_host)
        if dev_ts:
            from transmogrifai_tpu.utils.retry import with_device_retry
            fused = self._fused_program(dev_ts)
            params = {t.uid: t.device_params() for t in dev_ts}
            in_cols = {n: data.device_col(n)
                       for t in dev_ts for n in t.runtime_input_names()}
            # the fused layer program is the training/scoring hot path's
            # device dispatch: transient device errors (flaky tunnel, and
            # the chaos suite's injected faults) retry with backoff instead
            # of killing a run a checkpoint would otherwise have to resume
            with span("layer.apply_device", n_stages=len(dev_ts),
                      stages=",".join(t.operation_name for t in dev_ts)):
                outs = with_device_retry(fused, params, in_cols,
                                         site="dag.apply_layer")
            data = data.with_device_cols(outs)
            # record fitted vector metadata OUTSIDE the traced program
            # (ModelInsights' fallback reads the last stage's out_meta;
            # mutating self inside device_apply would tie freshness to jit
            # cache behavior)
            for t in dev_ts:
                m = getattr(outs.get(t.get_output().name), "metadata", None)
                if m is not None:
                    t.out_meta = m
        return data

    def _fused_program(self, dev_ts: Sequence[Transformer]):
        key = tuple(t.uid for t in dev_ts)
        cached = self._fused_cache.get(key)
        if cached is not None:
            return cached
        base = fuse_layer_program(dev_ts)  # precision-ok: training executor is f32 by contract
        compiled = lambda params, in_cols: base(params, {}, in_cols)  # noqa: E731
        self._fused_cache[key] = compiled
        return compiled

    # -- cross-layer fusion (round 14) ---------------------------------------
    def apply_fused(self, data: PipelineData,
                    layers: Sequence[Sequence[Transformer]]) -> PipelineData:
        """Apply a run of consecutive all-device layers as ONE jitted
        program. Intermediate level outputs never materialize in HBM
        between levels; every stage output still lands in the returned
        PipelineData (downstream layers, host pulls and keep-intermediate
        scoring read them exactly as before).

        Failure ladder: an OOM inside the fused program (the whole
        segment's intermediates are live at once) takes the
        ``ingest.fuse`` rung — re-apply the segment stage by stage, the
        smallest-peak execution order — instead of killing a run the
        per-layer path would have completed."""
        from transmogrifai_tpu.utils.faults import fault_point
        from transmogrifai_tpu.utils.profiling import ingest_counters
        from transmogrifai_tpu.utils.retry import with_device_retry
        stages = [t for layer in layers for t in layer]
        key = tuple(t.uid for t in stages)
        prog = self._fused_dag_cache.get(key)
        if prog is None:
            base = fuse_dag_program(layers)  # precision-ok: training executor is f32 by contract
            prog = lambda params, in_cols: base(params, {}, in_cols)  # noqa: E731
            self._fused_dag_cache[key] = prog
        params = {t.uid: t.device_params() for t in stages}
        produced = {t.get_output().name for t in stages}
        in_names = [n for t in stages for n in t.runtime_input_names()
                    if n not in produced]
        try:
            fault_point("ingest.fuse")
            in_cols = {n: data.device_col(n) for n in dict.fromkeys(in_names)}
            with span("fe.fused", n_stages=len(stages), n_layers=len(layers),
                      stages=",".join(t.operation_name for t in stages)):
                outs = with_device_retry(prog, params, in_cols,
                                         site="dag.apply_layer")
        except Exception as err:
            from transmogrifai_tpu.utils import resources
            from transmogrifai_tpu.utils.faults import FaultHarnessError
            if isinstance(err, FaultHarnessError):
                raise
            if not (resources.ladder_enabled()
                    and resources.is_resource_exhausted(err)):
                raise
            resources.record_degradation(
                "ingest.fuse", "stagewise", error=err,
                nStages=len(stages), nLayers=len(layers),
                nRows=data.n_rows)
            ingest_counters.fe_host_fallbacks += 1
            ingest_counters.fe_host_rows += data.n_rows * len(stages)
            return self._apply_stagewise(data, layers)
        ingest_counters.fe_fused_programs += 1
        ingest_counters.fe_fused_stages += len(stages)
        ingest_counters.fe_fused_rows += data.n_rows * len(stages)
        data = data.with_device_cols(outs)
        for t in stages:
            m = getattr(outs.get(t.get_output().name), "metadata", None)
            if m is not None:
                t.out_meta = m
        return data

    def _apply_stagewise(self, data: PipelineData,
                         layers: Sequence[Sequence[Transformer]]
                         ) -> PipelineData:
        """The ``ingest.fuse`` OOM rung: one stage = one small jitted
        program (``apply_layer`` over single-stage layers), intermediates
        materialized (and droppable) between stages — peak memory is a
        single stage's blocks. Staying on the jitted path keeps the rung
        bitwise-identical to the fused program (eager per-primitive
        execution codegens trig differently at the ULP level)."""
        for layer in layers:
            for t in layer:
                data = self.apply_layer(data, [t])
        return data


def fuse_layer_program(dev_ts: Sequence[Transformer], donate: bool = False,
                       precision: str = "f32"):
    """One jitted XLA program applying every device transformer of a layer.

    Signature: ``fused(params, donate_cols, keep_cols) -> {out name: col}``
    where the two column dicts together hold every runtime input. With
    ``donate=True`` the ``donate_cols`` buffers are donated to XLA (the
    online-serving steady state: per-batch input uploads whose last consumer
    is this layer are spent, halving resident batch memory); callers must
    not touch a donated column afterwards. Batch scoring passes everything
    in ``keep_cols`` — columns live in the executor's PipelineData and are
    reread by later layers and host pulls."""
    return fuse_dag_program([list(dev_ts)], donate=donate,
                            precision=precision)


def fuse_dag_program(layers: Sequence[Sequence[Transformer]],
                     donate: bool = False, precision: str = "f32"):
    """One jitted XLA program applying a run of consecutive ALL-device DAG
    levels — the round-14 generalization of :func:`fuse_layer_program`
    (which is the single-level special case and shares this builder, so
    serving's per-layer programs and the executor's segment programs are
    one code path).

    Signature and donation semantics match ``fuse_layer_program``; the
    returned dict holds EVERY stage output across the fused levels.
    Level-to-level intermediates flow through the traced program directly:
    a later level's stage reads an earlier level's output column from the
    in-program environment, never from HBM.

    ``precision`` selects the ladder rung the program computes at. The
    default ``"f32"`` rung traces exactly the pre-ladder program (no
    casts staged out at all). Non-f32 rungs cast float input leaves and
    per-stage float params to the rung's compute dtype in-trace
    (``QuantizedTensor`` weights dequantize, ``ExactTensor`` leaves keep
    their stored dtype) and cast float output leaves back to f32, so
    callers always see f32 results regardless of rung."""
    from transmogrifai_tpu.utils.precision import (
        cast_float_leaves, compute_dtype, materialize_tree)
    layer_list = [list(layer) for layer in layers]
    comp = compute_dtype(precision)

    def fused(params, donate_cols, keep_cols):
        env = {**donate_cols, **keep_cols}
        if comp is not None:
            env = cast_float_leaves(env, comp)
            params = cast_float_leaves(params, comp)
            params = materialize_tree(params, comp)
        out = {}
        for ts in layer_list:
            produced = {}
            for t in ts:
                cols = [env[n] for n in t.runtime_input_names()]
                # per-stage named scope: ops staged out here carry the
                # stage's operation name + uid in their XLA metadata, so
                # profiler-trace device slices attribute to stages, not
                # just layers/segments
                with device_scope(f"{t.operation_name}[{t.uid}]"):
                    produced[t.get_output().name] = t.device_apply(
                        params[t.uid], *cols)
            # a level's outputs become visible to LATER levels only
            # (within a level, stages are independent by construction)
            env.update(produced)
            out.update(produced)
        if comp is not None:
            out = cast_float_leaves(out, jnp.float32)
        return out

    return jax.jit(fused, donate_argnums=(1,) if donate else ())
