"""Per-tenant admission: weighted-fair token buckets in FRONT of the
lanes' queue backpressure.

The lane queues already bound memory, but they are per-model FIFO with
a shared device behind them: one hot tenant saturating its lane also
saturates the compile/dispatch thread pool and the device itself, so a
cold tenant's first request queues behind a flood it had no part in.
The admission layer meters each tenant at the door instead — a token
bucket per ``model_id``, refilled at ``rate_per_s x weight`` with a
``burst``-sized reservoir — and answers an empty bucket with the SAME
``BackpressureError`` (HTTP 503 + Retry-After) the queues use, so
every existing client retry loop (``absorb_backpressure``, the bench
clients, the router's spill path) already speaks the protocol and
"throttled" never becomes "dropped".

Retry-After is the bucket's own refill arithmetic (time until the
needed tokens exist), so a throttled tenant backs off exactly as long
as fairness requires, not a guessed constant.

``FairnessMetrics`` keeps the per-tenant evidence: admits, throttles,
**debt** (cumulative seconds of suggested wait — the integral of how
hard a tenant pushed past its share) and cold-start waits. Export is
cardinality-bounded: ``topk()`` ranks tenants by throttle pressure and
rolls the tail into one ``_other`` aggregate, mirroring the
Prometheus top-K policy.

Clocks are injectable everywhere (``clock=time.monotonic``) so tests
drive refill deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from transmogrifai_tpu.serving.batcher import BackpressureError

__all__ = ["TokenBucket", "TenantAdmission", "FairnessMetrics"]


class TokenBucket:
    """A standard token bucket: ``rate_per_s`` tokens/s refill into a
    ``burst``-sized reservoir; ``try_take`` returns 0.0 on admit or
    the seconds until the requested tokens will exist."""

    def __init__(self, rate_per_s: float, burst: float, *,
                 clock: Callable[[], float] = time.monotonic):
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        self.rate_per_s = float(rate_per_s)
        self.burst = max(float(burst), 1.0)
        self._clock = clock
        self._tokens = self.burst
        self._at = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        elapsed = max(now - self._at, 0.0)
        self._tokens = min(self.burst,
                           self._tokens + elapsed * self.rate_per_s)
        self._at = now

    def try_take(self, n: float = 1.0) -> float:
        """Take ``n`` tokens if available (returns 0.0), else leave the
        bucket untouched and return the wait in seconds until they
        would be."""
        with self._lock:
            now = self._clock()
            self._refill(now)
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate_per_s

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens


class FairnessMetrics:
    """Per-tenant admission evidence with bounded-cardinality export."""

    def __init__(self):
        self._lock = threading.Lock()
        #: tenant -> [admitted, throttled, debt_seconds]
        self._tenants: Dict[str, list] = {}
        self.cold_start_waits = 0
        self.cold_start_wait_s = 0.0

    def note_admitted(self, tenant: str) -> None:
        with self._lock:
            self._tenants.setdefault(tenant, [0, 0, 0.0])[0] += 1

    def note_throttled(self, tenant: str, wait_s: float) -> None:
        with self._lock:
            row = self._tenants.setdefault(tenant, [0, 0, 0.0])
            row[1] += 1
            row[2] += wait_s

    def note_cold_start_wait(self, wait_s: float) -> None:
        with self._lock:
            self.cold_start_waits += 1
            self.cold_start_wait_s += wait_s

    def tenant_rows(self) -> Dict[str, dict]:
        with self._lock:
            return {t: {"admitted": row[0], "throttled": row[1],
                        "debtSeconds": round(row[2], 6)}
                    for t, row in self._tenants.items()}

    def topk(self, k: int) -> tuple:
        """``(top, other)``: the ``k`` tenants under the most admission
        pressure (throttles, then admits — the busy ones are the ones
        worth a label) plus ONE aggregate of everyone else. ``k <= 0``
        means unlimited (other is None when nothing rolled up)."""
        rows = self.tenant_rows()
        ranked = sorted(
            rows.items(),
            key=lambda kv: (-kv[1]["throttled"], -kv[1]["admitted"],
                            kv[0]))
        if k <= 0 or len(ranked) <= k:
            return dict(ranked), None
        top = dict(ranked[:k])
        other = {"admitted": 0, "throttled": 0, "debtSeconds": 0.0,
                 "tenants": len(ranked) - k}
        for _, row in ranked[k:]:
            other["admitted"] += row["admitted"]
            other["throttled"] += row["throttled"]
            other["debtSeconds"] += row["debtSeconds"]
        other["debtSeconds"] = round(other["debtSeconds"], 6)
        return top, other

    def to_json(self, top_k: int = 20) -> dict:
        top, other = self.topk(top_k)
        with self._lock:
            doc = {"coldStartWaits": self.cold_start_waits,
                   "coldStartWaitSeconds":
                       round(self.cold_start_wait_s, 6)}
        doc["tenants"] = top
        if other is not None:
            doc["other"] = other
        return doc


class TenantAdmission:
    """Weighted-fair per-tenant gate: one :class:`TokenBucket` per
    ``model_id``, created on first request and refilled at
    ``rate_per_s x weight(tenant)``. ``admit`` raises
    :class:`BackpressureError` carrying the bucket's own refill time as
    Retry-After — the shared 503 protocol the whole stack retries."""

    def __init__(self, rate_per_s: float = 200.0,
                 burst: Optional[float] = None, *,
                 weights: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate_per_s = float(rate_per_s)
        #: one second of refill by default — enough burst to never
        #: throttle a tenant inside its steady-state share
        self.burst = float(burst) if burst is not None \
            else max(self.rate_per_s, 1.0)
        self.weights = dict(weights or {})
        self._clock = clock
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self.metrics = FairnessMetrics()

    def weight(self, tenant: str) -> float:
        return max(float(self.weights.get(tenant, 1.0)), 1e-6)

    def set_weight(self, tenant: str, weight: float) -> None:
        """Re-weight one tenant. Takes effect on its NEXT bucket refill
        (the bucket is rebuilt; accumulated tokens are forfeit — a
        deliberate penalty-free simplification: re-weighting is a rare
        operator action)."""
        with self._lock:
            self.weights[tenant] = float(weight)
            self._buckets.pop(tenant, None)

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                w = self.weight(tenant)
                bucket = self._buckets[tenant] = TokenBucket(
                    self.rate_per_s * w, self.burst * w,
                    clock=self._clock)
            return bucket

    def admit(self, tenant: str, n: float = 1.0) -> None:
        """Admit ``n`` requests for ``tenant`` or raise
        ``BackpressureError`` with the precise Retry-After."""
        wait = self._bucket(tenant).try_take(n)
        if wait > 0.0:
            self.metrics.note_throttled(tenant, wait)
            raise BackpressureError(
                f"tenant {tenant!r} over its admission rate "
                f"({self.rate_per_s:g}/s x weight "
                f"{self.weight(tenant):g}); retry in {wait:.3f}s",
                retry_after_s=wait)
        self.metrics.note_admitted(tenant)

    def to_json(self, top_k: int = 20) -> dict:
        doc = self.metrics.to_json(top_k)
        doc["ratePerS"] = self.rate_per_s
        doc["burst"] = self.burst
        if self.weights:
            doc["weights"] = dict(self.weights)
        return doc
