"""The tiered model store: HBM -> host RAM -> disk residency for a
fleet whose working set is much bigger than device memory.

Production multi-tenant AutoML is one model per org, thousands
registered at once, with brutal popularity skew: a handful of models
take most of the traffic while the long tail is touched hourly.
Keeping every fitted model's decoded arrays in host RAM (let alone its
compiled programs in HBM) does not survive that regime, so residency
becomes a three-tier ladder:

- **HBM tier** — compiled programs + deviced parameters in the fleet's
  shared ``ProgramCache`` (its own byte-budget LRU, unchanged).
- **RAM tier** — decoded-but-undeviced weight records: the loaded
  ``WorkflowModel`` (numpy arrays straight out of ``arrays.npz``).
  THIS module's budget: ``ram_budget_bytes`` bounds the accounted
  bytes of resident models, LRU beyond it.
- **cold tier** — a path and a stat-derived fingerprint, nothing else.
  A lazily registered model costs two ``os.stat`` calls until its
  first request.

**Demand paging**: the first score against a cold model walks the
ladder upward — ``touch`` loads the checkpoint (disk -> RAM, counted
and span-traced as ``tenancy.page_in``), and the lane's first dispatch
compiles into the shared cache (RAM -> HBM, counted by the existing
compile counters). Budget pressure walks it downward: the LRU victim's
lane is stopped (the fleet's ``on_demote`` hook), its model object
dropped, and its compiled programs evicted unless another resident
entry shares the fingerprint.

**Pressure-ladder composition** (PR 10): ``shed`` is the tier-demotion
rung — host RSS pressure demotes cold-tenant RAM residency FIRST,
before the serving ladder starts degrading hot tenants' quality
(precision/bucket shedding). Every shed records through
``resources.record_degradation`` under site ``tenancy.store`` so the
one degradation surface shows tier demotions next to bucket sheds.

Concurrency: page-ins single-flight per ``(model_id, version)`` via a
per-key reentrant lock (``page_lock``) that the fleet shares for lane
startup; victims selected under the store lock are *unpinned* entries
only (an in-flight page-in can never be chosen), and the demotion
itself re-checks residency under the victim's page lock, so a racing
re-page-in simply wins and the demotion becomes a no-op.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Optional

from transmogrifai_tpu.serving.registry import ModelState

__all__ = ["TieredModelStore", "TierMetrics", "RAM_BUDGET_ENV",
           "model_file_bytes"]

#: host-RAM byte budget for decoded model records (the RAM tier);
#: unset/0 = unbounded
RAM_BUDGET_ENV = "TRANSMOGRIFAI_MODEL_RAM_BUDGET"

#: newest cold-start walls kept for the percentile distribution the
#: bench commits (bounded: the counter is lifetime, the reservoir is not)
_COLD_START_SAMPLES = 4096


def model_file_bytes(path: str) -> int:
    """Stat-only RAM-footprint estimate of one saved model: the byte
    sizes of ``model.json`` + ``arrays.npz``. The decoded arrays
    dominate and land at roughly npz size (the format is uncompressed
    by default); the manifest's reconstructed stage graph rides along.
    Never opens either file."""
    from transmogrifai_tpu.serialization import ARRAYS_NPZ, MODEL_JSON
    total = 0
    for name in (MODEL_JSON, ARRAYS_NPZ):
        try:
            total += os.stat(os.path.join(path, name)).st_size
        except OSError:
            pass
    return total


class TierMetrics:
    """Thread-safe residency-ladder counters + the cold-start latency
    reservoir (the fleet's first-score SLA evidence)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.promotions_disk_ram = 0   # checkpoint loads (page-ins)
        self.promotions_ram_hbm = 0    # lane starts over a RAM record
        self.demotions_ram = 0         # RAM records dropped (budget/shed)
        self.demotions_hbm = 0         # program evictions forced by a
        #                              # RAM demotion (not LRU aging)
        self.sheds = 0                 # pressure-rung shed() calls
        self.prewarms = 0              # popularity-driven page-ins
        self.cold_starts = 0
        self.cold_start_wall_s = 0.0
        self._cold_walls: collections.deque = collections.deque(
            maxlen=_COLD_START_SAMPLES)

    def note_promotion_ram(self) -> None:
        with self._lock:
            self.promotions_disk_ram += 1

    def note_promotion_hbm(self) -> None:
        with self._lock:
            self.promotions_ram_hbm += 1

    def note_demotion(self, hbm_entries: int = 0) -> None:
        with self._lock:
            self.demotions_ram += 1
            self.demotions_hbm += int(hbm_entries)

    def note_shed(self) -> None:
        with self._lock:
            self.sheds += 1

    def note_prewarm(self) -> None:
        with self._lock:
            self.prewarms += 1

    def note_cold_start(self, wall_s: float) -> None:
        with self._lock:
            self.cold_starts += 1
            self.cold_start_wall_s += wall_s
            self._cold_walls.append(wall_s)

    def cold_start_percentiles_ms(self) -> dict:
        with self._lock:
            walls = sorted(self._cold_walls)
        if not walls:
            return {"count": 0, "p50": None, "p99": None, "max": None}

        def pct(p: float) -> float:
            i = min(int(p * (len(walls) - 1) + 0.5), len(walls) - 1)
            return round(walls[i] * 1e3, 3)

        return {"count": len(walls), "p50": pct(0.50), "p99": pct(0.99),
                "max": round(walls[-1] * 1e3, 3)}

    def to_json(self) -> dict:
        with self._lock:
            doc = {"promotionsDiskRam": self.promotions_disk_ram,
                   "promotionsRamHbm": self.promotions_ram_hbm,
                   "demotionsRam": self.demotions_ram,
                   "demotionsHbm": self.demotions_hbm,
                   "sheds": self.sheds,
                   "prewarms": self.prewarms,
                   "coldStarts": self.cold_starts,
                   "coldStartWallSeconds":
                       round(self.cold_start_wall_s, 6)}
        doc["coldStartMs"] = self.cold_start_percentiles_ms()
        return doc


class _Residency:
    __slots__ = ("nbytes", "pinned")

    def __init__(self, nbytes: int, pinned: bool):
        self.nbytes = int(nbytes)
        self.pinned = pinned


class TieredModelStore:
    """Byte-budgeted RAM tier over a ``ModelRegistry``'s entries, with
    demand paging up and LRU/pressure demotion down (module docstring
    for the full ladder)."""

    def __init__(self, registry, program_cache=None, *,
                 ram_budget_bytes: Optional[int] = None,
                 on_demote: Optional[Callable] = None,
                 on_precision_demote: Optional[Callable] = None):
        if ram_budget_bytes is None:
            env = os.environ.get(RAM_BUDGET_ENV)
            ram_budget_bytes = int(float(env)) if env else None
        self.registry = registry
        self.program_cache = program_cache
        self.ram_budget_bytes = ram_budget_bytes
        #: fleet hook, called (entry) under the victim's page lock
        #: BEFORE the model object drops — the lane stop + drain
        self.on_demote = on_demote
        #: fleet hook, called () FIRST by ``shed``: demote active
        #: lanes one precision rung (quality degradation, every tenant
        #: keeps serving) before any tenant is COLD-paged out entirely;
        #: returns the accounted bytes it released
        self.on_precision_demote = on_precision_demote
        self.metrics = TierMetrics()
        self._lock = threading.Lock()
        #: (model_id, version) -> _Residency, LRU order (oldest first)
        self._resident: "collections.OrderedDict" = \
            collections.OrderedDict()
        #: per-key page-in/demotion serialization (reentrant: the fleet
        #: wraps lane startup in the same lock)
        self._page_locks: dict = {}
        registry.attach_tier_store(self)

    # -- locks ---------------------------------------------------------------
    def page_lock(self, key: tuple) -> threading.RLock:
        with self._lock:
            lock = self._page_locks.get(key)
            if lock is None:
                lock = self._page_locks[key] = threading.RLock()
            return lock

    # -- accounting ----------------------------------------------------------
    @property
    def ram_bytes(self) -> int:
        with self._lock:
            return sum(r.nbytes for r in self._resident.values())

    @property
    def resident_count(self) -> int:
        with self._lock:
            return len(self._resident)

    def is_resident(self, model_id: str, version: str) -> bool:
        with self._lock:
            return (model_id, version) in self._resident

    # -- paging up -----------------------------------------------------------
    def touch(self, entry):
        """Ensure ``entry`` is RAM-resident and return its model object
        (the demand-paging entry point). A hit is one lock + an LRU
        move; a miss loads the checkpoint (span ``tenancy.page_in``),
        resolves the true content fingerprint of a lazily registered
        entry, charges the stat-estimated bytes against the budget, and
        demotes unpinned LRU victims beyond it."""
        key = (entry.model_id, entry.version)
        with self._lock:
            res = self._resident.get(key)
            if res is not None and entry.model is not None:
                self._resident.move_to_end(key)
                return entry.model
        with self.page_lock(key):
            # single-flight: a concurrent pager already finished
            with self._lock:
                res = self._resident.get(key)
                if res is not None and entry.model is not None:
                    self._resident.move_to_end(key)
                    return entry.model
            if entry.model is not None:
                # loaded but unaccounted (an eagerly registered entry
                # adopted into the tier): admit without re-loading
                nbytes = model_file_bytes(entry.path) if entry.path \
                    else 0
                victims = self._admit(key, nbytes, pinned=False)
                self._finish_demotions(victims)
                return entry.model
            if entry.path is None:
                raise ValueError(
                    f"model {entry.model_id!r} version "
                    f"{entry.version!r} has no path to page in from")
            nbytes = model_file_bytes(entry.path)
            # reserve pinned BEFORE the load: a concurrent pager's
            # victim scan must never pick an entry whose bytes are
            # about to land (the pin is what breaks the demote/page-in
            # lock cycle)
            victims = self._admit(key, nbytes, pinned=True)
            self._finish_demotions(victims)
            from transmogrifai_tpu.utils.events import events
            from transmogrifai_tpu.utils.tracing import span
            t0 = time.monotonic()
            try:
                with span("tenancy.page_in", model=entry.model_id,
                          version=entry.version, bytesEst=nbytes):
                    from transmogrifai_tpu.workflow import load_model
                    model = load_model(entry.path)
                    if entry.fingerprint.startswith("lazy:"):
                        from transmogrifai_tpu.checkpoint import (
                            model_fingerprint,
                        )
                        entry.fingerprint = model_fingerprint(
                            path=entry.path)
                    entry.model = model
            except BaseException:
                with self._lock:
                    self._resident.pop(key, None)
                raise
            with self._lock:
                res = self._resident.get(key)
                if res is not None:
                    res.pinned = False
            wall = time.monotonic() - t0
            self.metrics.note_promotion_ram()
            events.emit("tenancy.page_in", model=entry.model_id,
                        version=entry.version, bytes=nbytes,
                        wallMs=round(wall * 1e3, 3))
            return entry.model

    def _admit(self, key: tuple, nbytes: int,
               pinned: bool) -> list:
        """Insert/refresh one residency record and return the LRU
        victims (key, nbytes) the budget demands — selected here under
        the store lock, demoted by the caller outside it."""
        victims: list = []
        with self._lock:
            self._resident[key] = _Residency(nbytes, pinned)
            self._resident.move_to_end(key)
            if self.ram_budget_bytes:
                total = sum(r.nbytes for r in self._resident.values())
                for vkey in list(self._resident):
                    if total <= self.ram_budget_bytes \
                            or len(self._resident) <= 1:
                        break
                    res = self._resident[vkey]
                    if vkey == key or res.pinned:
                        continue
                    del self._resident[vkey]
                    total -= res.nbytes
                    victims.append((vkey, res.nbytes))
        return victims

    # -- paging down ---------------------------------------------------------
    def _finish_demotions(self, victims: list,
                          rung: Optional[str] = None) -> None:
        """Demote each selected victim: under ITS page lock, re-check
        it was not re-paged meanwhile, stop its lane (``on_demote``),
        drop the model object, and evict its compiled programs unless a
        still-resident entry shares the fingerprint."""
        from transmogrifai_tpu.serving.registry import UnknownModelError
        from transmogrifai_tpu.utils.events import events
        for vkey, nbytes in victims:
            with self.page_lock(vkey):
                with self._lock:
                    if vkey in self._resident:
                        continue    # re-paged while pending: it wins
                try:
                    entry = self.registry.get(*vkey)
                except UnknownModelError:
                    continue        # forgotten while pending
                if self.on_demote is not None:
                    self.on_demote(entry)
                entry.model = None
                if entry.state != ModelState.UNLOADED:
                    entry.state = ModelState.COLD
                hbm = 0
                if self.program_cache is not None \
                        and not entry.fingerprint.startswith("lazy:") \
                        and not self.registry.fingerprint_in_use(
                            entry.fingerprint):
                    hbm = self.program_cache.evict_model(
                        entry.fingerprint)
                self.metrics.note_demotion(hbm)
                self.registry.touch()
                events.emit("tenancy.demote", model=entry.model_id,
                            version=entry.version, bytes=nbytes,
                            hbmEntries=hbm, rung=rung)

    def shed(self, bytes_to_free: int) -> int:
        """The tier-demotion PRESSURE rung: demote least-recently-used
        unpinned residents until ``bytes_to_free`` accounted bytes are
        released (never the newest — the model serving the request that
        tripped the pressure must survive). Records through the
        resource ladder under site ``tenancy.store``. Returns the bytes
        freed.

        Precision demotion runs FIRST (the fleet's
        ``on_precision_demote`` hook): every active lane drops one rung
        of its precision ladder, releasing the demoted-from rung's
        compiled programs while every tenant KEEPS SERVING — only the
        shortfall COLD-pages residents out."""
        victims: list = []
        freed = 0
        if self.on_precision_demote is not None:
            freed = int(self.on_precision_demote() or 0)
            if freed:
                from transmogrifai_tpu.utils.resources import (
                    record_degradation,
                )
                record_degradation("tenancy.store", "demote_precision",
                                   bytesFreed=freed)
            if freed >= bytes_to_free:
                self.metrics.note_shed()
                return freed
        with self._lock:
            for vkey in list(self._resident):
                if freed >= bytes_to_free or len(self._resident) <= 1:
                    break
                res = self._resident[vkey]
                if res.pinned:
                    continue
                del self._resident[vkey]
                freed += res.nbytes
                victims.append((vkey, res.nbytes))
        if victims:
            from transmogrifai_tpu.utils.resources import (
                record_degradation,
            )
            self.metrics.note_shed()
            record_degradation(
                "tenancy.store", "demote_ram",
                modelsDemoted=len(victims), bytesFreed=freed)
            self._finish_demotions(victims, rung="demote_ram")
        return freed

    def note_unloaded(self, entry) -> None:
        """Registry hook: an explicit ``unload`` must release the RAM
        tier's accounted bytes (not just the device arrays) and the
        model's compiled programs when no other loaded entry shares the
        fingerprint. Called AFTER the registry dropped the model
        object."""
        key = (entry.model_id, entry.version)
        with self._lock:
            self._resident.pop(key, None)
        hbm = 0
        if self.program_cache is not None \
                and not entry.fingerprint.startswith("lazy:") \
                and not self.registry.fingerprint_in_use(
                    entry.fingerprint):
            hbm = self.program_cache.evict_model(entry.fingerprint)
        self.metrics.note_demotion(hbm)

    def to_json(self) -> dict:
        with self._lock:
            resident = len(self._resident)
            nbytes = sum(r.nbytes for r in self._resident.values())
        return {"residentModels": resident,
                "ramBytes": nbytes,
                "ramBudgetBytes": self.ram_budget_bytes,
                "metrics": self.metrics.to_json()}
