"""Popularity-driven prewarm: EWMA request rates rank the fleet, a
background daemon pages the top of the ranking in BEFORE traffic
does.

Demand paging alone makes every popularity shift a cold-start storm —
the tenant that just went hot eats a page-in + compile on the request
that made it hot. The tracker keeps a per-model exponentially-decayed
request rate (event-driven, O(1) per request, no sample buffers): on
each request batch ``rate = rate * exp(-dt/tau) + n/tau`` with ``tau =
half_life / ln 2``, which is the standard irregular-interval EWMA —
``rank()`` decays every rate to "now" so an idle model's score falls
toward zero even with no events arriving.

:class:`PrewarmDaemon` periodically takes the top-K ranking and pages
non-resident entries in through the fleet's ``ensure_hot``. It
composes with the PR 10 resource ladder rather than fighting it: under
host-RSS or disk pressure the daemon SHEDS cold residency (the
``tenancy.prewarm`` / ``prewarm_skip`` rung) instead of paging more
models in — prewarm is a luxury, pressure relief is not.

Clock injectable; daemon thread is named and daemonized like the other
background loops (supervisor, continuous trainer).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Tuple

__all__ = ["PopularityTracker", "PrewarmDaemon"]


class PopularityTracker:
    """Per-model exponentially-decayed request rate (requests/s)."""

    def __init__(self, half_life_s: float = 30.0, *,
                 clock: Callable[[], float] = time.monotonic):
        if half_life_s <= 0:
            raise ValueError(
                f"half_life_s must be > 0, got {half_life_s}")
        self.half_life_s = float(half_life_s)
        self._tau = self.half_life_s / math.log(2.0)
        self._clock = clock
        self._lock = threading.Lock()
        #: model_id -> [rate, last_update]
        self._rates: Dict[str, list] = {}

    def record(self, model_id: str, n: float = 1.0) -> None:
        now = self._clock()
        with self._lock:
            row = self._rates.get(model_id)
            if row is None:
                self._rates[model_id] = [n / self._tau, now]
                return
            rate, at = row
            row[0] = rate * math.exp(-(now - at) / self._tau) \
                + n / self._tau
            row[1] = now

    def rate(self, model_id: str) -> float:
        """The decayed-to-now request rate (requests/s estimate)."""
        now = self._clock()
        with self._lock:
            row = self._rates.get(model_id)
            if row is None:
                return 0.0
            return row[0] * math.exp(-(now - row[1]) / self._tau)

    def rank(self) -> List[Tuple[str, float]]:
        """All tracked models, hottest first, rates decayed to now —
        an idle model sinks even though no event touched it."""
        now = self._clock()
        with self._lock:
            decayed = [
                (mid, row[0] * math.exp(-(now - row[1]) / self._tau))
                for mid, row in self._rates.items()]
        return sorted(decayed, key=lambda kv: (-kv[1], kv[0]))

    def to_json(self, top_k: int = 20) -> dict:
        ranked = self.rank()
        shown = ranked if top_k <= 0 else ranked[:top_k]
        return {"tracked": len(ranked),
                "halfLifeSeconds": self.half_life_s,
                "top": [{"model": m, "rps": round(r, 4)}
                        for m, r in shown]}


class PrewarmDaemon:
    """Background loop: every ``interval_s``, page the ``top_k``
    hottest non-resident models in via ``fleet.ensure_hot`` — unless
    the resource ladder reports pressure, in which case shed instead
    (tier demotion and prewarm share ONE pressure policy; see module
    docstring)."""

    def __init__(self, fleet, tracker: PopularityTracker, *,
                 top_k: int = 8, interval_s: float = 2.0,
                 shed_fraction: float = 0.25):
        self.fleet = fleet
        self.tracker = tracker
        self.top_k = int(top_k)
        self.interval_s = float(interval_s)
        #: fraction of the RAM-tier budget to shed per pressured tick
        self.shed_fraction = float(shed_fraction)
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "PrewarmDaemon":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tenancy-prewarm", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — prewarm is best-effort
                from transmogrifai_tpu.utils.events import events
                events.emit_limited(
                    "tenancy.prewarm.error", 30.0,
                    "tenancy.prewarm_error",
                    error=f"{type(e).__name__}: {e}")

    def tick(self) -> int:
        """One prewarm pass; returns models paged in (0 under
        pressure). Split from ``_run`` so tests drive it inline."""
        from transmogrifai_tpu.utils.resources import (
            ladder_enabled,
            pressure_state,
            record_degradation,
        )
        store = getattr(self.fleet, "tenancy_store", None)
        if store is None:
            return 0
        if ladder_enabled():
            pressure = pressure_state()
            if pressure.get("rssPressure") \
                    or pressure.get("diskPressure") \
                    or pressure.get("enospcBackoffActive"):
                budget = store.ram_budget_bytes or store.ram_bytes
                shed = store.shed(
                    max(int(budget * self.shed_fraction), 1))
                record_degradation(
                    "tenancy.prewarm", "prewarm_skip",
                    bytesShed=shed)
                return 0
        warmed = 0
        for model_id, rate in self.tracker.rank()[:self.top_k]:
            if self._stop.is_set() or rate <= 0.0:
                break
            try:
                if self.fleet.ensure_hot(model_id):
                    store.metrics.note_prewarm()
                    warmed += 1
            except Exception:  # noqa: BLE001 — one cold model must not
                continue       # keep the rest of the ranking cold
        return warmed
