"""Multi-tenant model tiering: thousands of registered models, a
working set far bigger than HBM.

The subsystem has three legs (one module each):

- :mod:`~transmogrifai_tpu.tenancy.store` — the HBM -> host-RAM ->
  disk residency ladder with demand paging and pressure-rung demotion;
- :mod:`~transmogrifai_tpu.tenancy.fairness` — weighted-fair
  per-tenant token buckets in front of lane backpressure;
- :mod:`~transmogrifai_tpu.tenancy.popularity` — EWMA request-rate
  ranking driving the background prewarm daemon.

:class:`TenancyConfig` is the one knob surface ``FleetServer`` (and
the ``serve-fleet`` CLI) take: construct it, pass ``tenancy=cfg``, and
the fleet wires store + admission + prewarm around its existing
registry, program cache, and lanes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from transmogrifai_tpu.tenancy.fairness import (
    FairnessMetrics,
    TenantAdmission,
    TokenBucket,
)
from transmogrifai_tpu.tenancy.popularity import (
    PopularityTracker,
    PrewarmDaemon,
)
from transmogrifai_tpu.tenancy.store import (
    RAM_BUDGET_ENV,
    TieredModelStore,
    TierMetrics,
    model_file_bytes,
)

__all__ = ["TenancyConfig", "TieredModelStore", "TierMetrics",
           "TokenBucket", "TenantAdmission", "FairnessMetrics",
           "PopularityTracker", "PrewarmDaemon", "RAM_BUDGET_ENV",
           "model_file_bytes"]


@dataclass
class TenancyConfig:
    """Everything the fleet needs to run multi-tenant.

    Defaults are deliberately permissive — no RAM budget means the RAM
    tier only accounts (nothing demotes), and admission at 200 req/s
    per tenant only bites genuine floods."""
    #: host-RAM budget for decoded model records; None = env
    #: TRANSMOGRIFAI_MODEL_RAM_BUDGET, 0/unset = unbounded
    ram_budget_bytes: Optional[int] = None
    #: register checkpoints COLD (stat-only) and page in on first score
    lazy: bool = True
    #: per-tenant admission rate (tokens/s before weighting);
    #: None/0 disables admission entirely
    rate_per_s: Optional[float] = 200.0
    #: bucket depth; None = one second of refill
    burst: Optional[float] = None
    #: tenant -> weight multiplier for the fair refill
    weights: Dict[str, float] = field(default_factory=dict)
    #: popularity EWMA half-life
    half_life_s: float = 30.0
    #: prewarm this many hottest models per tick; 0 disables the daemon
    prewarm_top_k: int = 0
    prewarm_interval_s: float = 2.0
    #: precision-ladder target for every lane (``"f32"`` | ``"bf16"`` |
    #: ``"int8"`` | ``"auto"``): forwarded as the lanes' ``precision=``
    #: unless the fleet was given one explicitly. Under RAM pressure the
    #: store's ``shed`` demotes active lanes' precision FIRST — quality
    #: degradation before any tenant loses residency
    precision: str = "f32"
