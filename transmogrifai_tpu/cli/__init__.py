"""Developer CLI (reference ``cli/`` module): project generation + shell.

``python -m transmogrifai_tpu.cli gen --input data.csv --id id
--response label ProjectName`` emits a runnable AutoML project.
``python -m transmogrifai_tpu.cli shell`` opens the preloaded REPL
(reference ``repl/`` module analog).
"""

from transmogrifai_tpu.cli.gen import (
    ProblemKind, detect_problem_kind, generate_project,
)

__all__ = ["ProblemKind", "detect_problem_kind", "generate_project", "main"]


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser("transmogrifai_tpu")
    sub = ap.add_subparsers(dest="command", required=True)
    gen = sub.add_parser("gen", help="generate a project from a dataset")
    gen.add_argument("name", help="project name (output directory name)")
    gen.add_argument("--input", required=True,
                     help="CSV or parquet dataset path")
    gen.add_argument("--id", required=True, dest="id_col",
                     help="id column name")
    gen.add_argument("--response", required=True, help="response column")
    gen.add_argument("--schema", default=None,
                     help="optional Avro .avsc schema path")
    gen.add_argument("--output", default=".", help="output directory")
    gen.add_argument("--overwrite", action="store_true")
    sub.add_parser("shell", help="interactive shell with the framework "
                                 "preloaded (reference repl analog)")
    from transmogrifai_tpu.cli.continuous import (
        add_continuous_args, run_continuous,
    )
    from transmogrifai_tpu.cli.profile import add_profile_args, run_profile
    from transmogrifai_tpu.cli.scaleout import (
        add_scaleout_args, run_scaleout,
    )
    from transmogrifai_tpu.cli.explain import (
        add_explain_args, run_explain,
    )
    from transmogrifai_tpu.cli.serve import add_serve_args, run_serve
    from transmogrifai_tpu.cli.slo import add_slo_args, run_slo
    add_serve_args(sub.add_parser(
        "serve", help="online micro-batched scoring over a saved model "
                      "(jsonl/csv in, jsonl scores out); "
                      "--explain-top-k adds per-request LOCO "
                      "attributions"))
    add_explain_args(sub.add_parser(
        "explain", help="batch explainability: ModelInsights report + "
                        "per-row LOCO insight maps over a saved model"))
    add_scaleout_args(sub.add_parser(
        "scaleout", help="multi-process serving scale-out: consistent-"
                         "hash router + N replica fleet workers + "
                         "heartbeat supervision + autoscaling"))
    add_continuous_args(sub.add_parser(
        "continuous", help="closed-loop daemon: stream ingest + drift "
                           "detection + checkpoint-resumed retrain + "
                           "zero-downtime hot-swap"))
    add_profile_args(sub.add_parser(
        "profile", help="score a dataset under full tracing; emit a "
                        "Perfetto/chrome://tracing JSON + slowest-stages "
                        "table"))
    add_slo_args(sub.add_parser(
        "slo", help="SLO burn-rate status of a running serve/continuous "
                    "daemon (scrapes its /healthz + /metrics)"))
    from transmogrifai_tpu.cli.autopsy import add_autopsy_args, run_autopsy
    add_autopsy_args(sub.add_parser(
        "autopsy", help="pretty-print an incident dump / device-stall "
                        "autopsy (stall site, thread stacks, HBM "
                        "holders, pending dispatches, event tail)"))
    args = ap.parse_args(argv)

    if args.command == "shell":
        from transmogrifai_tpu.cli.shell import run_shell
        return run_shell()
    if args.command == "serve":
        return run_serve(args)
    if args.command == "explain":
        return run_explain(args)
    if args.command == "scaleout":
        return run_scaleout(args)
    if args.command == "continuous":
        return run_continuous(args)
    if args.command == "profile":
        return run_profile(args)
    if args.command == "slo":
        return run_slo(args)
    if args.command == "autopsy":
        return run_autopsy(args)
    if args.command == "gen":
        path = generate_project(
            name=args.name, input_path=args.input, id_col=args.id_col,
            response_col=args.response, output_dir=args.output,
            avro_schema_path=args.schema, overwrite=args.overwrite)
        print(f"Generated project at {path}")
        return 0
    return 1
