"""``transmogrifai_tpu continuous`` — the closed-loop AutoML daemon.

One long-running process that watches a stream directory, serves the
current model (``POST /score`` on ``--metrics-port``), detects feature
drift against the serving model's training distribution, retrains on
the accumulated window when drift triggers (resuming from checkpoints
if interrupted), and hot-swaps the new version behind the live endpoint
through the shadow-parity gate::

    python -m transmogrifai_tpu.cli continuous \
        --workflow myproj.pipeline:runner \
        --stream-dir incoming/ --pattern '*.csv' \
        --model models/churn --state-dir loop_state/ \
        --window-batches 4 --js-threshold 0.2 --metrics-port 9100

``--workflow module:attr`` imports the retrain template: a ``Workflow``
(result features wired) or a ``WorkflowRunner`` (its ``.workflow`` is
used). ``--model`` loads the initial serving model; omit it to
BOOTSTRAP — the first full window trains v1 before serving starts.
Stream files must carry the response column (labeled data arriving
continuously). The loop's manifest, stream checkpoint, and per-retrain
training checkpoints all live under ``--state-dir``: kill the process
at any point and re-run the same command to resume with zero lost rows.
See docs/CONTINUOUS.md.
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys

__all__ = ["add_continuous_args", "run_continuous"]


def add_continuous_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("--workflow", required=True,
                    help="module:attr of the retrain template (a Workflow "
                         "or WorkflowRunner)")
    sp.add_argument("--stream-dir", required=True,
                    help="directory watched for micro-batch files")
    sp.add_argument("--pattern", default="*",
                    help="stream file glob (default '*')")
    sp.add_argument("--state-dir", required=True,
                    help="loop manifest + stream checkpoint + retrain "
                         "checkpoints (the resume root)")
    sp.add_argument("--model", default=None,
                    help="initial saved model dir; omit to bootstrap "
                         "from the first stream window")
    sp.add_argument("--reference", default=None,
                    help="batch file (csv/avro/parquet) sampling the "
                         "initial model's TRAINING data; pins the drift "
                         "reference. Without it a loop given --model "
                         "adopts the first stream window — which reads "
                         "drift ~0 on an already-shifted stream")
    sp.add_argument("--model-id", default="live",
                    help="serving endpoint id (default 'live')")
    sp.add_argument("--window-batches", type=int, default=4,
                    help="micro-batches per drift window (default 4)")
    sp.add_argument("--max-buffer-batches", type=int, default=8,
                    help="retrain-buffer bound in batches (default 8)")
    sp.add_argument("--poll-interval-s", type=float, default=1.0)
    sp.add_argument("--timeout-s", type=float, default=None,
                    help="stop after this long without new files "
                         "(default: run forever)")
    sp.add_argument("--max-windows", type=int, default=None,
                    help="stop after closing this many windows "
                         "(default: run forever)")
    sp.add_argument("--drift-metric", choices=("js", "psi"), default="js")
    sp.add_argument("--js-threshold", type=float, default=0.25)
    sp.add_argument("--psi-threshold", type=float, default=0.25)
    sp.add_argument("--fill-delta-threshold", type=float, default=0.25)
    sp.add_argument("--label-delta-threshold", type=float, default=0.25)
    sp.add_argument("--consecutive-windows", type=int, default=2,
                    help="hysteresis: breaching windows required to "
                         "trigger (default 2)")
    sp.add_argument("--cooldown-windows", type=int, default=2,
                    help="windows after a trigger/promotion with "
                         "triggers suppressed (default 2)")
    sp.add_argument("--shadow-tolerance", type=float, default=1.0,
                    help="hot-swap shadow-gate max abs score diff "
                         "(default 1.0: schema/NaN sanity — drift "
                         "retrains legitimately change scores)")
    sp.add_argument("--staleness-bound-s", type=float, default=None,
                    help="warn when drift-to-promotion exceeds this")
    sp.add_argument("--max-retrain-attempts", type=int, default=3)
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics, /healthz and POST /score "
                         "on this port (0 = ephemeral; port printed to "
                         "stderr)")
    sp.add_argument("--metrics-host", default="127.0.0.1")
    sp.add_argument("--report", default=None,
                    help="write the final loop report JSON here "
                         "(always printed to stdout)")
    sp.add_argument("--trace-out", default=None,
                    help="export the daemon's span ring as a Perfetto/"
                         "chrome://tracing JSON on shutdown")
    sp.add_argument("--access-log-sample", type=float, default=0.0,
                    help="fraction of HTTP requests emitted as "
                         "structured http.access events (0 = off)")
    sp.add_argument("--slo", default=None, dest="slo_path",
                    help="SLO objectives JSON; a staleness objective is "
                         "implied by --staleness-bound-s. Exports "
                         "transmogrifai_slo_* and folds fast-burn "
                         "alerts into /healthz readiness")
    sp.add_argument("--no-events-spill", action="store_true",
                    help="disable the durable flight-recorder spill "
                         "(state_dir/events.jsonl; on by default)")
    sp.add_argument("--resource-ladder", choices=("on", "off"),
                    default=None,
                    help="override the adaptive degradation ladder "
                         "(docs/ROBUSTNESS.md 'Resource exhaustion'): "
                         "OOM-failed retrains halve the row window and "
                         "back off instead of burning the attempt "
                         "budget at the same shape. Default: on "
                         "(TRANSMOGRIFAI_RESOURCE_LADDER)")


def _load_workflow(spec: str):
    from transmogrifai_tpu.runner import WorkflowRunner
    from transmogrifai_tpu.workflow import Workflow
    mod, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"--workflow {spec!r}: expected module:attr")
    obj = getattr(importlib.import_module(mod), attr)
    if isinstance(obj, WorkflowRunner):
        return obj.workflow
    if isinstance(obj, Workflow):
        return obj
    raise TypeError(f"--workflow {spec!r} resolved to "
                    f"{type(obj).__name__}; expected a Workflow or "
                    "WorkflowRunner")


def run_continuous(args: argparse.Namespace) -> int:
    from transmogrifai_tpu.cli.serve import (
        GracefulShutdown, _observability_setup, _observability_teardown,
        install_sigterm_handler,
    )
    from transmogrifai_tpu.continuous import ContinuousLoop, DriftConfig
    from transmogrifai_tpu.workflow import load_model

    slo = _observability_setup(args, "transmogrifai_tpu.continuous")
    workflow = _load_workflow(args.workflow)
    initial_model = load_model(args.model) if args.model else None
    drift = DriftConfig(
        metric=args.drift_metric,
        js_threshold=args.js_threshold,
        psi_threshold=args.psi_threshold,
        fill_delta_threshold=args.fill_delta_threshold,
        label_delta_threshold=args.label_delta_threshold,
        consecutive_windows=args.consecutive_windows,
        cooldown_windows=args.cooldown_windows)
    def announce(lp):
        if lp.metrics_http is not None:
            print(f"# serving: http://127.0.0.1:{lp.metrics_http.port}"
                  "/score (+ /metrics, /healthz)", file=sys.stderr)

    loop = ContinuousLoop(
        workflow, args.stream_dir, args.state_dir,
        model_id=args.model_id, pattern=args.pattern,
        initial_model=initial_model, reference_path=args.reference,
        drift=drift,
        window_batches=args.window_batches,
        max_buffer_batches=args.max_buffer_batches,
        poll_interval_s=args.poll_interval_s,
        timeout_s=args.timeout_s, max_windows=args.max_windows,
        max_retrain_attempts=args.max_retrain_attempts,
        shadow_tolerance=args.shadow_tolerance,
        staleness_bound_s=args.staleness_bound_s,
        metrics_port=args.metrics_port, metrics_host=args.metrics_host,
        access_log_sample=args.access_log_sample, slo=slo,
        events_spill=not args.no_events_spill,
        on_started=announce)
    print(f"# continuous loop: watching {args.stream_dir!r} "
          f"(pattern {args.pattern!r}), serving model id "
          f"{args.model_id!r}, state under {args.state_dir!r}",
          file=sys.stderr)
    install_sigterm_handler()
    try:
        report = loop.run()
    except GracefulShutdown:
        # SIGTERM: loop.run()'s finally already drained the fleet,
        # snapshotted serving totals and released the endpoint —
        # classified as a routine shutdown (no incident dump). Report
        # and exit 0 like a stream-timeout stop.
        print("# SIGTERM: continuous loop drained and stopped cleanly",
              file=sys.stderr)
        report = loop.report()
    finally:
        _observability_teardown(args)
    print(json.dumps(report, indent=2, default=str))
    if args.report:
        with open(args.report, "w") as fh:
            json.dump(report, fh, indent=2, default=str)
    c = report["counters"]
    print(f"# {report['windows']} window(s): {c['driftTriggers']} "
          f"trigger(s), {c['retrains']} retrain(s), "
          f"{c['promotions']} promotion(s), {c['rollbacks']} "
          f"rollback(s); active version "
          f"{report['activeVersion']}", file=sys.stderr)
    return 0
