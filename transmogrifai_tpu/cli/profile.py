"""``transmogrifai_tpu profile`` — score a dataset under full tracing and
emit the merged Perfetto/chrome://tracing timeline plus a top-K
slowest-stages table.

    python -m transmogrifai_tpu.cli profile --model model_dir \
        --input data.csv --trace-out trace.json --metrics-out metrics.json

The run opens one ``jax.profiler`` trace (device timeline, when the
backend supports it), records the hierarchical host span tree
(``utils/tracing.py``) through ingest, every DAG stage, and the fused
layer dispatches, then fuses both into ``--trace-out`` — open it at
chrome://tracing or https://ui.perfetto.dev. The phase/stage tables print
to stderr; ``--metrics-out`` saves the same ``AppMetrics`` json. See
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["add_profile_args", "run_profile"]


def add_profile_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("--model", required=True, help="saved model directory")
    sp.add_argument("--input", required=True,
                    help="dataset to score: .csv / .parquet / .avro path")
    sp.add_argument("--trace-out", required=True,
                    help="write the merged chrome-trace JSON here")
    sp.add_argument("--metrics-out", default=None,
                    help="write the AppMetrics json here")
    sp.add_argument("--top-k", type=int, default=10,
                    help="slowest-stages table size (default 10)")
    sp.add_argument("--no-device-trace", action="store_true",
                    help="skip the jax.profiler device trace (host spans "
                         "only; cheaper, works on any backend)")


def _reader_for(path: str):
    from transmogrifai_tpu.readers.factory import DataReaders
    if path.endswith(".csv"):
        return DataReaders.Simple.csv_auto(path)
    if path.endswith((".parquet", ".pq")):
        return DataReaders.Simple.parquet(path)
    if path.endswith(".avro"):
        return DataReaders.Simple.avro(path)
    raise ValueError(f"unsupported input {path!r}: expected "
                     ".csv/.parquet/.avro")


def run_profile(args: argparse.Namespace) -> int:
    from transmogrifai_tpu.utils.profiling import OpStep, profiler
    from transmogrifai_tpu.workflow import load_model

    trace_dir = None
    if not args.no_device_trace:
        import tempfile
        trace_dir = tempfile.mkdtemp(prefix="transmogrifai_profile_")
    profiler.reset(app_name="transmogrifai_tpu.profile",
                   trace_dir=trace_dir)
    model = load_model(args.model)
    reader = _reader_for(args.input)
    try:
        with profiler.phase(OpStep.SCORING):
            scores = model.score(reader)
        metrics = profiler.finalize()
    finally:
        if trace_dir is not None:
            import shutil
            shutil.rmtree(trace_dir, ignore_errors=True)
    summary = metrics.export_chrome_trace(args.trace_out)
    if args.metrics_out:
        metrics.save(args.metrics_out)
    print(metrics.pretty(top_k=args.top_k), file=sys.stderr)
    print(f"# scored {scores.n_rows} rows; trace -> {args.trace_out} "
          f"({json.dumps(summary)}); open at chrome://tracing or "
          "https://ui.perfetto.dev", file=sys.stderr)
    return 0
