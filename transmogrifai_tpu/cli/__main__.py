import sys

from transmogrifai_tpu.cli import main

sys.exit(main())
