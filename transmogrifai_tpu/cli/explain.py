"""``transmogrifai_tpu explain`` — batch explainability over a saved model.

Two outputs from one fitted checkpoint:

- the merged **ModelInsights report** (``insights/model_insights.py``:
  selected model + validation table, top contributions, label
  correlations, SanityChecker drops, sensitive features) — printed as a
  pretty table by default, ``--json`` for the full document;
- with ``--input``, per-row **LOCO record insights**
  (``insights/loco.py``) over a jsonl/csv request file: one JSON line of
  ``{group name: delta}`` per input row, through the cached compiled
  LOCO programs (repeat batches are pure program-cache hits).

    python -m transmogrifai_tpu.cli explain --model model_dir \
        --input requests.jsonl --output insights.jsonl --top-k 10

The line-rate twin of this offline surface is ``serve --explain-top-k``
(and the HTTP ``{"explain": true}`` field) — see docs/INSIGHTS.md.
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["add_explain_args", "run_explain"]


def add_explain_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("--model", required=True,
                    help="saved model directory (serialization.save_model)")
    sp.add_argument("--input", default=None,
                    help="request rows (.jsonl / .csv, or '-' for stdin): "
                         "emit per-row LOCO insight maps")
    sp.add_argument("--output", default="-",
                    help="insights jsonl path, or '-' for stdout")
    sp.add_argument("--top-k", type=int, default=20,
                    help="attributions kept per row (default 20)")
    sp.add_argument("--aggregation", default="LeaveOutVector",
                    choices=("LeaveOutVector", "Avg"),
                    help="LOCO group aggregation strategy (reference "
                         "VectorAggregationStrategy; default "
                         "LeaveOutVector)")
    sp.add_argument("--json", action="store_true",
                    help="print the ModelInsights report as full JSON "
                         "instead of the pretty tables")
    sp.add_argument("--no-report", action="store_true",
                    help="skip the ModelInsights report (LOCO only)")


def _read_rows(path: str):
    from transmogrifai_tpu.cli.serve import _read_rows
    return _read_rows(path)


def run_explain(args: argparse.Namespace) -> int:
    import numpy as np

    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.workflow import load_model

    model = load_model(args.model)
    if not args.no_report:
        insights = model.model_insights()
        if args.json:
            print(insights.json())
        else:
            print(insights.pretty())
    if args.input is None:
        return 0

    from transmogrifai_tpu.insights.loco import (
        RecordInsightsLOCO, loco_programs,
    )
    from transmogrifai_tpu.serving.explain import resolve_prediction_stage
    try:
        pstage, vec_name, _, _ = resolve_prediction_stage(model)
    except ValueError as e:
        print(f"explain: {e}", file=sys.stderr)
        return 2
    loco = RecordInsightsLOCO(model=pstage, top_k=args.top_k,
                              aggregation_strategy=args.aggregation)

    rows = list(_read_rows(args.input))
    if not rows:
        print("explain: --input holds no rows", file=sys.stderr)
        return 2
    from transmogrifai_tpu.types.feature_types import nullable_base
    raw_names = {f.name for f in model.raw_features}
    cols: dict = {}
    for f in model.raw_features:
        vals = [r.get(f.name) for r in rows]
        # requests legitimately omit the label (cf. CompiledScorer)
        ftype = nullable_base(f.ftype) if f.is_response else f.ftype
        cols[f.name] = fr.HostColumn.from_values(ftype, vals)
    unknown = set(rows[0]) - raw_names
    if unknown:
        print(f"# ignoring non-raw request keys: {sorted(unknown)}",
              file=sys.stderr)
    data = model.transform(fr.HostFrame(cols))
    insight_col = loco.host_apply(data.host_col(vec_name))

    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        for m in insight_col.values:
            out.write(json.dumps(
                {k: float(v) for k, v in sorted(
                    m.items(), key=lambda kv: -abs(float(kv[1])))},
                default=str) + "\n")
    finally:
        if out is not sys.stdout:
            out.close()
    stats = loco_programs.stats()
    print(f"# explained {len(rows)} rows through {stats['insertions']} "
          f"compiled LOCO program(s) ({stats['hits']} cache hits)",
          file=sys.stderr)
    return 0
