"""``transmogrifai_tpu slo`` — SLO burn-rate status of a running daemon.

Scrapes a live ``cli serve`` / ``cli continuous`` endpoint (its
``/healthz`` readiness doc and the ``transmogrifai_slo_*`` series on
``/metrics``) and renders one status table: per objective and alert the
short/long-window burn rates, the configured factor, and whether the
alert FIRES (both windows over the factor) — plus the endpoint's overall
readiness, which a firing fast-burn alert flips::

    python -m transmogrifai_tpu.cli slo --url http://127.0.0.1:9100
    python -m transmogrifai_tpu.cli slo --port 9100 --watch 5

Exit status: 0 all quiet, 1 an alert is firing (scriptable:
``cli slo || page-someone``), 2 the endpoint is unreachable or exports
no SLO series (the daemon was started without ``--slo`` /
``--staleness-bound-s``).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request

__all__ = ["add_slo_args", "run_slo"]

#: one exposition label: name="value" with escaped chars allowed in the
#: value — operator-chosen SLO names may contain ',' or '=' and must
#: not crash the parser
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def add_slo_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("--url", default=None,
                    help="scrape endpoint base url "
                         "(e.g. http://127.0.0.1:9100)")
    sp.add_argument("--port", type=int, default=None,
                    help="shorthand for --url http://<host>:<port>")
    sp.add_argument("--host", default="127.0.0.1",
                    help="host for --port (default loopback)")
    sp.add_argument("--timeout-s", type=float, default=5.0)
    sp.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="re-render every SECONDS until interrupted")
    sp.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw /healthz slo block as JSON")


def _fetch(url: str, timeout_s: float):
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode()


def _render(health: dict, metrics_text: str) -> tuple[str, bool, bool]:
    """(table text, any_alert_firing, has_series) from the scraped
    surfaces."""
    from transmogrifai_tpu.utils.table import Table
    slo = health.get("slo") or {}
    rows = []
    # burn rates + per-alert firing states come from the gauge series
    # (the authoritative export — /healthz only carries the
    # objective-level rollup, which would paint a quiet fast alert
    # FIRING whenever its objective's slow alert tickets)
    burns: dict = {}
    alert_firing: dict = {}
    for line in metrics_text.splitlines():
        if not line.startswith(("transmogrifai_slo_burn_rate{",
                                "transmogrifai_slo_alert_firing{")):
            continue
        labels_part = line[line.index("{") + 1:line.rindex("}")]
        labels = dict(_LABEL_RE.findall(labels_part))
        value = line.rsplit(" ", 1)[-1]
        key = (labels.get("slo"), labels.get("alert"))
        if line.startswith("transmogrifai_slo_alert_firing{"):
            alert_firing[key] = float(value) > 0
        else:
            burns[key + (labels.get("window"),)] = value
    firing_names = set(slo.get("firing", []))
    seen = sorted({(s, a) for s, a, _w in burns})
    for name, alert in seen:
        short = burns.get((name, alert, "short"),
                          burns.get((name, alert, "current"), "-"))
        long_ = burns.get((name, alert, "long"), "-")
        firing = alert_firing.get((name, alert),
                                  name in firing_names)
        rows.append((name, alert, short, long_,
                     "FIRING" if firing else "ok"))
    status = health.get("status", "?")
    ready = health.get("ready")
    title = (f"SLO status — endpoint {status!r}, "
             f"ready={'yes' if ready else 'no'}")
    if not rows:
        return (f"{title}\n(no transmogrifai_slo_* series: daemon "
                "started without --slo/--staleness-bound-s)",
                bool(firing_names), False)
    table = Table(["objective", "alert", "burn(short)", "burn(long)",
                   "state"], rows, title=title)
    return str(table), bool(firing_names), True


def run_slo(args: argparse.Namespace) -> int:
    url = args.url
    if url is None and args.port is not None:
        url = f"http://{args.host}:{args.port}"
    if url is None:
        print("slo: pass --url or --port (the daemon's --metrics-port)",
              file=sys.stderr)
        return 2
    url = url.rstrip("/")
    while True:
        try:
            health = json.loads(_fetch(f"{url}/healthz", args.timeout_s))
            # --json renders /healthz only: don't force the daemon to
            # build the full exposition just to throw it away
            metrics_text = "" if args.as_json else \
                _fetch(f"{url}/metrics", args.timeout_s)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"slo: cannot scrape {url}: {e}", file=sys.stderr)
            return 2
        if args.as_json:
            print(json.dumps({"status": health.get("status"),
                              "ready": health.get("ready"),
                              "slo": health.get("slo")}, indent=2))
            firing = bool((health.get("slo") or {}).get("firing"))
            has_series = health.get("slo") is not None
        else:
            text, firing, has_series = _render(health, metrics_text)
            print(text)
        # the documented scriptable contract: 0 quiet, 1 firing, 2 no
        # SLO surface at all (a misconfigured daemon must not read as
        # "all quiet" to `cli slo || page-someone`)
        code = 1 if firing else (0 if has_series else 2)
        if args.watch is None:
            return code
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return code
