"""Interactive framework shell (reference ``repl/`` module analog).

The reference build declares a ``repl`` project that drops users into a
Spark shell with the TransmogrifAI imports preloaded. The TPU-native
equivalent is a Python REPL with the whole public surface ready: feature
builders, the transmogrifier, selectors, evaluators, workflow, readers,
testkit generators, and the feature DSL installed — plus a banner stating
the backend (TPU/CPU) and device count.

``python -m transmogrifai_tpu.cli shell``
(uses IPython when available, stdlib ``code.interact`` otherwise).
"""

from __future__ import annotations

__all__ = ["make_namespace", "banner", "run_shell"]


def make_namespace() -> dict:
    """The preloaded REPL namespace — everything a session needs, named
    exactly as the docs/examples use them."""
    import numpy as np

    from transmogrifai_tpu import dsl  # noqa: F401 — installs DSL methods
    from transmogrifai_tpu import frame as fr
    from transmogrifai_tpu.evaluators import (
        OpBinaryClassificationEvaluator, OpMultiClassificationEvaluator,
        OpRegressionEvaluator,
    )
    from transmogrifai_tpu.features.builder import FeatureBuilder
    from transmogrifai_tpu.filters import RawFeatureFilter
    from transmogrifai_tpu.local import (
        import_sklearn, import_xgboost_json, make_score_function,
    )
    from transmogrifai_tpu.ops.transmogrifier import transmogrify
    from transmogrifai_tpu.preparators import SanityChecker
    from transmogrifai_tpu.readers import DataReaders
    from transmogrifai_tpu.selector import (
        BinaryClassificationModelSelector, DataSplitter,
        MultiClassificationModelSelector, RegressionModelSelector,
    )
    from transmogrifai_tpu.types import feature_types as ft
    from transmogrifai_tpu.workflow import Workflow, load_model

    ns = dict(
        np=np, fr=fr, ft=ft, dsl=dsl,
        FeatureBuilder=FeatureBuilder, transmogrify=transmogrify,
        SanityChecker=SanityChecker, RawFeatureFilter=RawFeatureFilter,
        DataReaders=DataReaders, Workflow=Workflow, load_model=load_model,
        BinaryClassificationModelSelector=BinaryClassificationModelSelector,
        MultiClassificationModelSelector=MultiClassificationModelSelector,
        RegressionModelSelector=RegressionModelSelector,
        DataSplitter=DataSplitter,
        OpBinaryClassificationEvaluator=OpBinaryClassificationEvaluator,
        OpMultiClassificationEvaluator=OpMultiClassificationEvaluator,
        OpRegressionEvaluator=OpRegressionEvaluator,
        make_score_function=make_score_function,
        import_sklearn=import_sklearn,
        import_xgboost_json=import_xgboost_json,
    )
    try:
        from transmogrifai_tpu.testkit import random_data
        ns["random_data"] = random_data
    except Exception:  # failure-ok: optional shell-namespace preload
        pass
    return ns


def banner(ns: dict | None = None) -> str:
    import jax

    try:
        devs = jax.devices()
        backend = f"{devs[0].platform} x{len(devs)}"
    except Exception as e:  # dead tunnel etc: the shell still opens (failure-ok: banner reports backend unavailable)
        backend = f"unavailable ({type(e).__name__})"
    names = ", ".join(sorted(ns if ns is not None else make_namespace()))
    return (f"transmogrifai_tpu shell — backend: {backend}\n"
            f"preloaded: {names}\n"
            "quick start: survived, predictors = ... ; "
            "features = transmogrify(predictors); "
            "Workflow().set_reader(...).set_result_features(...).train()")


def run_shell() -> int:
    # honor JAX_PLATFORMS before any backend init (site plugins override
    # the env var; a dead TPU tunnel would otherwise hang the banner)
    from transmogrifai_tpu.utils.platform import respect_jax_platforms
    respect_jax_platforms()
    ns = make_namespace()
    text = banner(ns)
    try:
        from IPython import start_ipython
        print(text)
        start_ipython(argv=[], user_ns=ns,
                      display_banner=False)  # type: ignore[call-arg]
    except ImportError:
        import code
        code.interact(banner=text, local=ns)
    return 0
