"""``transmogrifai_tpu autopsy`` — pretty-print an incident dump.

The flight recorder's ``dump_incident`` snapshots and the devicewatch
stall autopsies are raw JSON/JSONL with no reader; this subcommand
renders one as ``utils/table.py`` tables: the stall site and wait, a
thread-stack digest, the top HBM holders, the pending-dispatch
inventory, and the recent event tail::

    python -m transmogrifai_tpu.cli autopsy incidents/incident_...json
    python -m transmogrifai_tpu.cli autopsy state_dir            # newest
    python -m transmogrifai_tpu.cli autopsy state_dir/events.jsonl

Accepts an incident JSON file, a directory (the newest
``incident_*.json`` under it or its ``incidents/`` subdir is picked),
or a flight-recorder ``events.jsonl`` spill (the event tail plus any
``device.stall`` records render). Exit status: 0 rendered, 2 nothing
readable at the path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

__all__ = ["add_autopsy_args", "run_autopsy"]


def add_autopsy_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("path",
                    help="incident .json, a directory holding incidents, "
                         "or a flight-recorder events.jsonl spill")
    sp.add_argument("--events", type=int, default=20, metavar="N",
                    help="event-tail rows to render (default 20)")
    sp.add_argument("--frames", type=int, default=8, metavar="N",
                    help="innermost stack frames per thread (default 8)")


def _newest_incident(dir_path: str) -> Optional[str]:
    """The newest ``incident_*.json`` under ``dir_path`` or its
    ``incidents/`` subdir (dump_incident's layout)."""
    for root in (os.path.join(dir_path, "incidents"), dir_path):
        try:
            files = sorted(f for f in os.listdir(root)
                           if f.startswith("incident_")
                           and f.endswith(".json"))
        except OSError:
            continue
        if files:
            return os.path.join(root, files[-1])
    return None


def _fmt_ts(ts) -> str:
    import datetime
    try:
        return datetime.datetime.fromtimestamp(
            float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError, OverflowError):
        return str(ts)


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return "-"


def _event_rows(events: list, n: int) -> list[tuple]:
    rows = []
    for ev in events[-n:]:
        attrs = {k: v for k, v in ev.items()
                 if k not in ("ts", "kind", "traceId")}
        summary = ", ".join(f"{k}={v}" for k, v in list(attrs.items())[:4])
        rows.append((_fmt_ts(ev.get("ts")), str(ev.get("kind", "?")),
                     str(ev.get("traceId") or "-"), summary[:70]))
    return rows


def _render_events_tail(events: list, n: int) -> None:
    from transmogrifai_tpu.utils.table import Table
    rows = _event_rows(events, n)
    if rows:
        print(Table(["time", "kind", "trace", "attrs"], rows,
                    title=f"event tail (newest {len(rows)})"))


def _render_incident(doc: dict, args: argparse.Namespace) -> None:
    from transmogrifai_tpu.utils.table import Table
    autopsy = (doc.get("extra") or {}).get("autopsy") or {}
    wait = autopsy.get("wait") or {}
    head_rows = [("reason", str(doc.get("reason", "?"))),
                 ("written at", _fmt_ts(doc.get("at")))]
    if wait:
        head_rows += [("stall site", str(wait.get("site", "?"))),
                      ("blocked wait", str(wait.get("name", "?"))),
                      ("blocked thread", str(wait.get("thread", "?"))),
                      ("elapsed (s)", str(wait.get("elapsedSeconds",
                                                   "?"))),
                      ("deadline (s)", str(wait.get("timeoutSeconds",
                                                    "?")))]
    print(Table(["field", "value"], head_rows, title="incident"))

    stacks = autopsy.get("threadStacks") or []
    if stacks:
        rows = []
        blocked_name = wait.get("thread")
        for s in stacks:
            frames = (s.get("frames") or [])[-args.frames:]
            mark = "*" if s.get("threadName") == blocked_name else ""
            rows.append((f"{s.get('threadName', '?')}{mark}",
                         "y" if s.get("daemon") else "n",
                         " <- ".join(reversed(frames))[:120]))
        print(Table(["thread (*=stalled)", "daemon",
                     "stack (innermost first)"], rows,
                    title=f"thread stacks ({len(stacks)})"))

    buffers = autopsy.get("liveBuffers") or {}
    buckets = buffers.get("buckets") or []
    if buckets:
        rows = [(b.get("shape", "?"), b.get("dtype", "?"),
                 b.get("count", 0), _fmt_bytes(b.get("bytes")))
                for b in buckets]
        print(Table(["shape", "dtype", "count", "bytes"], rows,
                    title=f"top {len(rows)} HBM holders "
                          f"(of {buffers.get('arrays', '?')} live arrays, "
                          f"{_fmt_bytes(buffers.get('totalBytes'))})"))
    census = autopsy.get("hbmCensus") or {}
    if census.get("devices"):
        rows = [(d.get("device", "?"), _fmt_bytes(d.get("bytesInUse")),
                 _fmt_bytes(d.get("peakBytesInUse")),
                 _fmt_bytes(d.get("bytesLimit")))
                for d in census["devices"]]
        print(Table(["device", "in use", "peak", "limit"], rows,
                    title="per-device HBM census"))

    pend = autopsy.get("pendingDispatches") or []
    if pend:
        rows = []
        for p in pend:
            attrs = {k: v for k, v in p.items()
                     if k not in ("site", "ageSeconds")}
            rows.append((str(p.get("site", "?")),
                         str(p.get("ageSeconds", "?")),
                         ", ".join(f"{k}={v}"
                                   for k, v in attrs.items())[:60]))
        print(Table(["site", "age (s)", "labels"], rows,
                    title=f"pending dispatches ({len(pend)})"))
    else:
        print("(no pending dispatches in the ledger)")

    compile_state = autopsy.get("compile") or {}
    if compile_state:
        rows = [("programs compiled", compile_state.get("programs", 0)),
                ("compile wall (s)", compile_state.get("wallSeconds", 0)),
                ("slowest compile (s)",
                 compile_state.get("maxWallSeconds", 0)),
                ("builds in progress",
                 compile_state.get("inProgress", 0)),
                ("slow compiles", compile_state.get("slowCompiles", 0))]
        print(Table(["compile state", "value"], rows))

    _render_events_tail(doc.get("events") or [], args.events)


def run_autopsy(args: argparse.Namespace) -> int:
    path = args.path
    if os.path.isdir(path):
        found = _newest_incident(path)
        if found is None:
            print(f"autopsy: no incident_*.json under {path!r} (or its "
                  "incidents/ subdir)", file=sys.stderr)
            return 2
        path = found
    if not os.path.exists(path):
        print(f"autopsy: {path!r} does not exist", file=sys.stderr)
        return 2
    if path.endswith(".jsonl"):
        events = []
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError as e:
            print(f"autopsy: cannot read {path!r}: {e}", file=sys.stderr)
            return 2
        stalls = [e for e in events if e.get("kind") == "device.stall"]
        if stalls:
            from transmogrifai_tpu.utils.table import Table
            rows = [(_fmt_ts(e.get("ts")), str(e.get("site", "?")),
                     str(e.get("elapsedSeconds", "?")),
                     str(e.get("pendingDispatches", "?")),
                     _fmt_bytes(e.get("hbmBytesInUse")))
                    for e in stalls]
            print(Table(["time", "site", "elapsed (s)", "pending",
                         "HBM in use"], rows,
                        title=f"device.stall events ({len(stalls)})"))
        _render_events_tail(events, args.events)
        return 0
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"autopsy: cannot read {path!r}: {e}", file=sys.stderr)
        return 2
    print(f"# {path}")
    _render_incident(doc, args)
    return 0
