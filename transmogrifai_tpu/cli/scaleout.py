"""``transmogrifai_tpu scaleout`` — multi-process serving scale-out.

``serve`` (default) runs the whole stack in one control process — the
consistent-hash router on ``--port``, ``--replicas`` worker
subprocesses (each a full fleet server on an ephemeral port),
heartbeat supervision with crash respawn, and optionally the
SLO/pressure-driven autoscaler::

    python -m transmogrifai_tpu.cli scaleout serve \
        --model-dir models/ --replicas 4 --port 8300 \
        --state-dir scale_state/ --autoscale --max-replicas 8

``status --url http://127.0.0.1:8300`` prints the replica table and
router counters from a running stack's ``/healthz``. Rolling
promotions are an embedding API (``ScaleoutStack.rolling_swap`` /
``ReplicaSupervisor.rolling_swap``) — see docs/SERVING.md
("Scale-out").

SIGTERM drains: replicas finish in-flight requests before the stack
exits (the same contract ``cli serve``/``cli continuous`` honor).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

__all__ = ["add_scaleout_args", "run_scaleout"]


def add_scaleout_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("mode", nargs="?", default="serve",
                    choices=("serve", "status"),
                    help="serve (default): run router + replicas; "
                         "status: query a running stack's /healthz")
    sp.add_argument("--model-dir", default=None,
                    help="saved-model register root (<id>/ or "
                         "<id>/<version>/ layouts; required for serve)")
    sp.add_argument("--state-dir", default=None,
                    help="heartbeats + replica logs (required for "
                         "serve)")
    sp.add_argument("--replicas", type=int, default=2)
    sp.add_argument("--port", type=int, default=0,
                    help="router port (0 = ephemeral, printed to "
                         "stderr)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--spill", type=int, default=2,
                    help="backpressure spillover bound: how many ring "
                         "successors a 503'd request may try "
                         "(default 2)")
    sp.add_argument("--max-batch", type=int, default=64)
    sp.add_argument("--queue-capacity", type=int, default=256)
    sp.add_argument("--wire", choices=("binary", "json"),
                    default="binary",
                    help="binary (default): replicas negotiate the "
                         "columnar frame wire alongside JSON/NDJSON "
                         "(the router forwards frames opaquely either "
                         "way); json: pin replicas JSON-only — frame "
                         "POSTs answer 400 (docs/WIRE.md)")
    sp.add_argument("--no-artifacts", action="store_true",
                    help="skip the shared compiled-program artifact "
                         "layer")
    sp.add_argument("--warmup", default=None,
                    help="JSON file mapping model id -> one "
                         "representative row; published as artifact "
                         "manifests so every replica warms before "
                         "traffic")
    sp.add_argument("--autoscale", action="store_true",
                    help="drive replica count from SLO burn + queue "
                         "depth (up) and host pressure (down)")
    sp.add_argument("--min-replicas", type=int, default=1)
    sp.add_argument("--max-replicas", type=int, default=8)
    sp.add_argument("--slo", default=None, dest="slo_path",
                    help="SLO objectives JSON evaluated over ROUTER-"
                         "observed traffic (also the autoscaler's "
                         "scale-up signal)")
    sp.add_argument("--duration-s", type=float, default=None,
                    help="serve for this long then drain and exit "
                         "(default: until SIGTERM/^C)")
    sp.add_argument("--url", default=None,
                    help="status mode: the running router's base URL")
    sp.add_argument("--events-out", default=None,
                    help="spill the control process's flight-recorder "
                         "events to this JSONL")
    sp.add_argument("--resource-ladder", choices=("on", "off"),
                    default=None, help="override the degradation "
                         "ladder for the control process")


def _status(url: str) -> int:
    import urllib.request
    with urllib.request.urlopen(f"{url.rstrip('/')}/healthz",
                                timeout=10) as resp:
        doc = json.loads(resp.read())
    reps = doc.get("replicas", {})
    print(f"status: {doc.get('status')}  ready: {doc.get('ready')}  "
          f"replicas: {len(reps)}")
    for rid, rep in sorted(reps.items()):
        print(f"  {rid:>6}  {rep.get('state', '?'):>9}  "
              f"127.0.0.1:{rep.get('port')}")
    router = doc.get("router", {})
    print(f"router: completed={router.get('completed')} "
          f"failed={router.get('failed')} "
          f"spillovers={router.get('spillovers')} "
          f"retries={router.get('retries')} "
          f"markdowns={router.get('markdowns')}")
    return 0 if doc.get("ready") else 1


def run_scaleout(args: argparse.Namespace) -> int:
    if args.mode == "status":
        if not args.url:
            print("scaleout status: pass --url http://host:port",
                  file=sys.stderr)
            return 2
        return _status(args.url)
    if not args.model_dir or not args.state_dir:
        print("scaleout serve: --model-dir and --state-dir are "
              "required", file=sys.stderr)
        return 2
    from transmogrifai_tpu.cli.serve import (
        GracefulShutdown, _observability_setup, install_sigterm_handler,
    )
    from transmogrifai_tpu.scaleout.stack import ScaleoutStack
    slo = _observability_setup(args, "transmogrifai_tpu.scaleout")
    warm = None
    if args.warmup:
        with open(args.warmup) as fh:
            warm = json.load(fh)
    worker_args = ["--max-batch", str(args.max_batch),
                   "--queue-capacity", str(args.queue_capacity),
                   "--wire", args.wire]
    stack = ScaleoutStack(
        args.model_dir, args.state_dir,
        replicas=args.replicas, port=args.port, host=args.host,
        spill=args.spill, slo=slo, autoscale=args.autoscale,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        warm_rows=warm, worker_args=worker_args,
        use_artifacts=not args.no_artifacts)
    install_sigterm_handler()
    t_end = (time.monotonic() + args.duration_s
             if args.duration_s is not None else None)
    try:
        stack.start()
        print(f"# scaleout: router on http://{args.host}:{stack.port} "
              f"(POST /score/<model>, /healthz, /metrics), "
              f"{stack.supervisor.replica_count()} replica(s)",
              file=sys.stderr)
        while t_end is None or time.monotonic() < t_end:
            time.sleep(0.5)
    except (KeyboardInterrupt, GracefulShutdown):
        print("# scaleout: draining replicas and stopping cleanly",
              file=sys.stderr)
    finally:
        status = stack.status()
        stack.stop()
    print(json.dumps(status, indent=2, default=str))
    return 0
