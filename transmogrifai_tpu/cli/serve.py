"""``transmogrifai_tpu serve`` — score requests through the online server.

Reads request rows (JSON-lines from a file or stdin, or a CSV with schema
inference), replays them through ``serving.ScoringServer`` (micro-batched
compiled scoring, backpressure, row-path degradation), writes one JSON
score line per request, and optionally dumps the serving-metrics snapshot:

    python -m transmogrifai_tpu.cli serve --model model_dir \
        --input requests.jsonl --output scores.jsonl --metrics metrics.json \
        --max-batch 256 --max-wait-ms 2 --queue-capacity 1024

Rejected rows (strict validation) and per-row scoring failures emit an
``{"error": ...}`` line at the request's position — output line i always
answers input line i.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Iterable, Optional

__all__ = ["add_serve_args", "run_serve"]


def add_serve_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("--model", required=True, help="saved model directory")
    sp.add_argument("--input", default="-",
                    help="requests: .jsonl / .csv path, or '-' for "
                         "JSON-lines on stdin (default)")
    sp.add_argument("--output", default="-",
                    help="scores jsonl path, or '-' for stdout (default)")
    sp.add_argument("--metrics", default=None,
                    help="write the serving-metrics snapshot here")
    sp.add_argument("--max-batch", type=int, default=256)
    sp.add_argument("--max-wait-ms", type=float, default=2.0)
    sp.add_argument("--queue-capacity", type=int, default=1024)
    sp.add_argument("--timeout-ms", type=float, default=None,
                    help="per-request deadline while queued")
    sp.add_argument("--no-strict", action="store_true",
                    help="skip admission-time raw-key validation")
    sp.add_argument("--no-warmup", action="store_true",
                    help="skip padding-bucket warmup before traffic")
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (Prometheus exposition) and "
                         "/healthz on this port while scoring (0 = "
                         "ephemeral; port printed to stderr)")
    sp.add_argument("--metrics-host", default="127.0.0.1",
                    help="bind address for the scrape endpoint (use "
                         "0.0.0.0 for an external scraper; default "
                         "loopback)")


def _read_rows(path: str) -> Iterable[dict]:
    if path == "-":
        for line in sys.stdin:
            line = line.strip()
            if line:
                yield json.loads(line)
        return
    if path.endswith(".csv"):
        from transmogrifai_tpu.readers.csv import CSVReader
        yield from CSVReader(path).read()  # schema-inferred typed rows
        return
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield json.loads(line)


def run_serve(args: argparse.Namespace) -> int:
    from transmogrifai_tpu.serving import ScoringServer
    from transmogrifai_tpu.workflow import load_model

    model = load_model(args.model)
    server = ScoringServer(
        model, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        queue_capacity=args.queue_capacity,
        default_timeout_ms=args.timeout_ms, strict=not args.no_strict,
        metrics_port=args.metrics_port, metrics_host=args.metrics_host)

    out = sys.stdout if args.output == "-" else open(args.output, "w")
    t0 = time.monotonic()
    n = n_err = 0
    #: (index, future | error) in submit order; drained whenever the
    #: window exceeds the queue so output order == input order without
    #: materializing every request first
    window: list[tuple[int, Any]] = []
    warmed = args.no_warmup

    def drain() -> None:
        nonlocal n_err
        for _, item in window:
            if isinstance(item, Exception):
                doc = {"error": f"{type(item).__name__}: {item}"}
                n_err += 1
            else:
                try:
                    doc = item.result()
                except Exception as e:  # noqa: BLE001 — per-row report
                    doc = {"error": f"{type(e).__name__}: {e}"}
                    n_err += 1
            out.write(json.dumps(doc, default=str) + "\n")
        window.clear()

    try:
        server.start()
        if server.metrics_http is not None:
            print(f"# metrics: http://127.0.0.1:{server.metrics_http.port}"
                  "/metrics (+ /healthz)", file=sys.stderr)
        for i, row in enumerate(_read_rows(args.input)):
            if not warmed:
                server.start(warmup_row=row)  # non-fatal on a bad row
                warmed = True
            try:
                window.append((i, server.submit_blocking(row)))
            except KeyError as e:  # strict admission reject
                window.append((i, e))
            n += 1
            if len(window) >= args.queue_capacity:
                drain()
        drain()
    finally:
        server.stop()
        if out is not sys.stdout:
            out.close()
    wall = time.monotonic() - t0
    snap = server.snapshot()
    if args.metrics:
        with open(args.metrics, "w") as fh:
            json.dump(snap, fh, indent=2)
    lat = snap["latencyMs"]
    print(f"# served {n} requests ({n_err} errored) in {wall:.2f}s "
          f"({n / max(wall, 1e-9):.0f} rps), p50={lat['p50']}ms "
          f"p95={lat['p95']}ms p99={lat['p99']}ms "
          f"degraded={snap['degraded']['entries']}", file=sys.stderr)
    return 0
