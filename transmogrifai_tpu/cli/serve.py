"""``transmogrifai_tpu serve`` — score requests through the online server.

Reads request rows (JSON-lines from a file or stdin, or a CSV with schema
inference), replays them through ``serving.ScoringServer`` (micro-batched
compiled scoring, backpressure, row-path degradation), writes one JSON
score line per request, and optionally dumps the serving-metrics snapshot:

    python -m transmogrifai_tpu.cli serve --model model_dir \
        --input requests.jsonl --output scores.jsonl --metrics metrics.json \
        --max-batch 256 --max-wait-ms 2 --queue-capacity 1024

Multi-model: ``--model-dir`` registers every fingerprinted checkpoint
under a directory into a ``serving.FleetServer`` (flat ``<id>/`` or
versioned ``<id>/<version>/`` layouts) and routes each request row by its
``--model-field`` key (default ``model``, popped before scoring; rows
without it go to ``--default-model``, or to the sole registered model):

    python -m transmogrifai_tpu.cli serve --model-dir models/ \
        --input requests.jsonl --metrics-port 9100

Rejected rows (strict validation), unknown model ids, and per-row scoring
failures emit an ``{"error": ...}`` line at the request's position —
output line i always answers input line i.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Iterable, Optional

__all__ = ["add_serve_args", "run_serve", "GracefulShutdown",
           "install_sigterm_handler"]


class GracefulShutdown(SystemExit):
    """Raised in the main thread by the SIGTERM handler: drain what was
    admitted, write outputs/snapshots, exit 0 — a supervised daemon
    (systemd stop, the scale-out supervisor's SIGTERM, a k8s preStop)
    must not die mid-batch with unwritten output. A ``SystemExit``
    subclass so the continuous loop's graceful-vs-incident
    classification treats it as a routine shutdown, never a
    postmortem."""


def install_sigterm_handler() -> bool:
    """Install the drain-and-exit SIGTERM handler (main thread only;
    returns False elsewhere — embedded callers drive stop themselves)."""
    import signal
    import threading

    def _handler(signum, frame):
        raise GracefulShutdown(0)

    if threading.current_thread() is not threading.main_thread():
        return False
    signal.signal(signal.SIGTERM, _handler)
    return True


def add_serve_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("--model", default=None, help="saved model directory "
                    "(single-model serving)")
    sp.add_argument("--model-dir", default=None,
                    help="fleet serving: register every saved model under "
                         "this directory (<id>/ or <id>/<version>/ "
                         "layouts) and route rows by --model-field")
    sp.add_argument("--model-field", default="model",
                    help="request-row key naming the target model id "
                         "(fleet mode; popped before scoring; default "
                         "'model')")
    sp.add_argument("--default-model", default=None,
                    help="model id for rows without --model-field (fleet "
                         "mode; default: the sole registered model)")
    sp.add_argument("--input", default="-",
                    help="requests: .jsonl / .csv path, or '-' for "
                         "JSON-lines on stdin (default)")
    sp.add_argument("--output", default="-",
                    help="scores jsonl path, or '-' for stdout (default)")
    sp.add_argument("--metrics", default=None,
                    help="write the serving-metrics snapshot here")
    sp.add_argument("--max-batch", type=int, default=256)
    sp.add_argument("--max-wait-ms", type=float, default=2.0)
    sp.add_argument("--queue-capacity", type=int, default=1024)
    sp.add_argument("--timeout-ms", type=float, default=None,
                    help="per-request deadline while queued")
    sp.add_argument("--no-strict", action="store_true",
                    help="skip admission-time raw-key validation")
    sp.add_argument("--no-warmup", action="store_true",
                    help="skip padding-bucket warmup before traffic")
    sp.add_argument("--explain-top-k", type=int, default=None,
                    help="serve every request through the EXPLAIN lane: "
                         "each output line gains an ordered "
                         "'explanations' list of the top-K LOCO "
                         "attributions (docs/INSIGHTS.md). HTTP scoring "
                         "(--metrics-port, fleet mode) also accepts an "
                         "opt-in per-request {\"explain\": true|K} field")
    sp.add_argument("--wire", choices=("json", "binary"),
                    default="json",
                    help="replay encoding: json (default) submits each "
                         "row as-is; binary packs contiguous rows into "
                         "length-prefixed columnar frames (up to "
                         "--max-batch rows each) and drives the full "
                         "encode -> column-path score -> decode wire "
                         "round trip (docs/WIRE.md). Output is "
                         "identical either way: one JSON score line "
                         "per input line")
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (Prometheus exposition) and "
                         "/healthz on this port while scoring (0 = "
                         "ephemeral; port printed to stderr)")
    sp.add_argument("--metrics-host", default="127.0.0.1",
                    help="bind address for the scrape endpoint (use "
                         "0.0.0.0 for an external scraper; default "
                         "loopback)")
    sp.add_argument("--trace-out", default=None,
                    help="export the span ring as a Perfetto/"
                         "chrome://tracing JSON on shutdown (the "
                         "long-running-daemon analog of runner "
                         "--trace-out)")
    sp.add_argument("--access-log-sample", type=float, default=0.0,
                    help="fraction of HTTP requests emitted as "
                         "structured http.access events through the "
                         "flight recorder (0 = off, default)")
    sp.add_argument("--slo", default=None, dest="slo_path",
                    help="SLO objectives JSON (docs/OBSERVABILITY.md "
                         "'SLOs'): exports transmogrifai_slo_* burn-rate "
                         "series and folds firing fast-burn alerts into "
                         "/healthz readiness")
    sp.add_argument("--events-out", default=None,
                    help="spill flight-recorder events to this JSONL "
                         "file (grep a trace id to reconstruct a "
                         "request's path)")
    sp.add_argument("--tenancy", choices=("on", "off"), default=None,
                    help="fleet mode: multi-tenant model tiering "
                         "(docs/SERVING.md 'Multi-tenant fleet') — "
                         "checkpoints register COLD (stat-only) and "
                         "demand-page on first score, with per-tenant "
                         "admission in front of the lanes. Implied by "
                         "any other --tenant*/--model-ram-budget/"
                         "--prewarm-top-k flag")
    sp.add_argument("--model-ram-budget", type=int, default=None,
                    help="host-RAM byte budget for decoded model "
                         "records (the RAM tier): LRU tenants demote "
                         "back to COLD beyond it. Default: "
                         "TRANSMOGRIFAI_MODEL_RAM_BUDGET, unset = "
                         "unbounded")
    sp.add_argument("--tenant-rate", type=float, default=None,
                    help="per-tenant admission rate in requests/s "
                         "before weighting (default 200; 0 disables "
                         "admission). Throttled requests get 503 + "
                         "Retry-After, never a drop")
    sp.add_argument("--prewarm-top-k", type=int, default=None,
                    help="page this many of the hottest tenants in "
                         "ahead of traffic each prewarm tick "
                         "(popularity EWMA ranking; 0 = no daemon, "
                         "the default)")
    sp.add_argument("--precision",
                    choices=("auto", "f32", "bf16", "int8"),
                    default="f32",
                    help="precision-ladder target (docs/SERVING.md "
                         "'Precision ladder'): serving starts on the "
                         "f32 master rung and PROMOTES to bf16/int8 "
                         "only after the shadow gate proves the rung's "
                         "scores within tolerance of f32 on live rows; "
                         "'auto' climbs the whole ladder. Under memory "
                         "pressure the active rung demotes (gate "
                         "skipped, counted) BEFORE any padding bucket "
                         "is shed. Default f32: ladder off")
    sp.add_argument("--resource-ladder", choices=("on", "off"),
                    default=None,
                    help="override the adaptive degradation ladder "
                         "(docs/ROBUSTNESS.md 'Resource exhaustion'): "
                         "on OOM the server sheds padding buckets / "
                         "evicts cold cache entries instead of pinning "
                         "the row path. Default: on "
                         "(TRANSMOGRIFAI_RESOURCE_LADDER)")


def _read_rows(path: str) -> Iterable[dict]:
    if path == "-":
        for line in sys.stdin:
            line = line.strip()
            if line:
                yield json.loads(line)
        return
    if path.endswith(".csv"):
        from transmogrifai_tpu.readers.csv import CSVReader
        yield from CSVReader(path).read()  # schema-inferred typed rows
        return
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                yield json.loads(line)


class _FrameChunk:
    """One ``--wire binary`` window item: a frame of contiguous input
    rows for one model. ``item`` is the frame future, or the
    encode/admission exception; drain fans the framed reply back out to
    one score document per row, at each row's input slot."""

    __slots__ = ("model_id", "rows", "item")

    def __init__(self, model_id: str, rows: list, item: Any):
        self.model_id = model_id
        self.rows = rows
        self.item = item


def _submit_frame_chunk(submit_fn, model_id: str,
                        rows: list) -> _FrameChunk:
    """rows -> request frame BYTES -> decode -> submit. The replay
    deliberately runs the client codec in both directions so ``--wire
    binary`` proves the wire end to end, not just the column scorer."""
    from transmogrifai_tpu.serving import wireformat as wf
    from transmogrifai_tpu.serving.batcher import absorb_backpressure
    try:
        frame = wf.decode_frame(wf.encode_rows(model_id, rows))
        fut = absorb_backpressure(lambda: submit_fn(frame))
        return _FrameChunk(model_id, rows, fut)
    except Exception as e:  # noqa: BLE001 — chunk-level admission error
        return _FrameChunk(model_id, rows, e)


def _frame_chunk_docs(chunk: _FrameChunk) -> list:
    """Settle one frame chunk into per-row score documents (reply
    columns -> reply frame bytes -> decode -> rows). A chunk-level
    failure errors every row of the chunk — the frame is the admission
    unit; per-row failures inside a scored frame ride the reply's
    ``error`` column instead."""
    from transmogrifai_tpu.serving import wireformat as wf
    n = len(chunk.rows)
    item = chunk.item
    if not isinstance(item, Exception):
        try:
            kind, result = item.result()
            cols = wf.reply_columns(result, n) if kind == "columns" \
                else wf.rows_to_reply_columns(result)
            reply = wf.decode_frame(wf.encode_frame(
                chunk.model_id, cols, n, kind=wf.KIND_REPLY))
            return wf.reply_to_rows(reply)
        except Exception as e:  # noqa: BLE001 — per-chunk report
            item = e
    return [{"error": f"{type(item).__name__}: {item}"}
            for _ in range(n)]


def _observability_setup(args, app_name: str):
    """Shared serve/continuous daemon observability plumbing: start a
    profiled session for ``--trace-out``, point the flight-recorder
    spill at ``--events-out``, load ``--slo`` objectives. Returns the
    parsed objectives (or None)."""
    if getattr(args, "resource_ladder", None):
        import os
        from transmogrifai_tpu.utils.resources import LADDER_ENV
        os.environ[LADDER_ENV] = \
            "1" if args.resource_ladder == "on" else "0"
    if getattr(args, "trace_out", None):
        from transmogrifai_tpu.utils.profiling import profiler
        profiler.reset(app_name=app_name)
    if getattr(args, "events_out", None):
        import os
        from transmogrifai_tpu.utils.events import events
        from transmogrifai_tpu.utils.resources import set_watch_path
        events.configure(spill_path=args.events_out)
        # the spill dir is this daemon's write root: point the default
        # disk-pressure probes at its filesystem instead of the cwd's —
        # and land device-stall autopsy dumps beside the spill (an
        # explicit TRANSMOGRIFAI_DEVICEWATCH_DIR wins)
        write_root = os.path.dirname(os.path.abspath(args.events_out))
        set_watch_path(write_root)
        from transmogrifai_tpu.utils import devicewatch
        if devicewatch.watchdog.incident_dir is None:
            devicewatch.configure(incident_dir=write_root)
    slo = None
    if getattr(args, "slo_path", None):
        from transmogrifai_tpu.utils.slo import load_objectives
        slo = load_objectives(args.slo_path)
    return slo


def _observability_teardown(args) -> None:
    """Flush the spill; export the daemon's span ring as a chrome trace."""
    if getattr(args, "events_out", None):
        from transmogrifai_tpu.utils.events import events
        events.flush()
    if getattr(args, "trace_out", None):
        from transmogrifai_tpu.utils.profiling import profiler
        try:
            summary = profiler.finalize().export_chrome_trace(
                args.trace_out)
            print(f"# trace -> {args.trace_out} ({json.dumps(summary)}); "
                  "open at chrome://tracing or https://ui.perfetto.dev",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — a failed export must not fail the run
            print(f"# trace export failed: {type(e).__name__}: {e}",
                  file=sys.stderr)


def run_serve(args: argparse.Namespace) -> int:
    from transmogrifai_tpu.serving import ScoringServer
    from transmogrifai_tpu.workflow import load_model

    if (args.model is None) == (args.model_dir is None):
        print("serve: pass exactly one of --model (single model) or "
              "--model-dir (fleet)", file=sys.stderr)
        return 2
    if args.wire == "binary" and args.explain_top_k is not None:
        print("serve: --wire binary and --explain-top-k are exclusive "
              "in replay — explained replays ride the row lane (HTTP "
              "frame clients opt in per request via frame meta "
              "{\"explain\": K})", file=sys.stderr)
        return 2
    slo = _observability_setup(args, "transmogrifai_tpu.serve")
    if args.model_dir is not None:
        return _run_serve_fleet(args, slo)
    model = load_model(args.model)
    explaining = args.explain_top_k is not None
    server = ScoringServer(
        model, max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        queue_capacity=args.queue_capacity,
        default_timeout_ms=args.timeout_ms, strict=not args.no_strict,
        metrics_port=args.metrics_port, metrics_host=args.metrics_host,
        access_log_sample=args.access_log_sample, slo=slo,
        explain=explaining,
        explain_top_k=args.explain_top_k if explaining else 5,
        precision=args.precision)

    out = sys.stdout if args.output == "-" else open(args.output, "w")
    t0 = time.monotonic()
    n = n_err = 0
    binary = args.wire == "binary"
    #: (index, future | error | _FrameChunk) in submit order; drained
    #: whenever the window exceeds the queue so output order == input
    #: order without materializing every request first
    window: list[tuple[int, Any]] = []
    warmed = args.no_warmup
    #: --wire binary: rows awaiting their frame (flushed at --max-batch)
    chunk: list = []
    frame_mid = os.path.basename(
        os.path.normpath(args.model)) or "model"

    def flush_chunk() -> None:
        if chunk:
            window.append((-1, _submit_frame_chunk(
                server.submit_frame, frame_mid, chunk[:])))
            chunk.clear()

    def drain() -> None:
        nonlocal n_err
        for _, item in window:
            if isinstance(item, _FrameChunk):
                for doc in _frame_chunk_docs(item):
                    if doc.get("error") is not None:
                        n_err += 1
                    out.write(json.dumps(doc, default=str) + "\n")
                continue
            if isinstance(item, Exception):
                doc = {"error": f"{type(item).__name__}: {item}"}
                n_err += 1
            else:
                try:
                    doc = item.result()
                except Exception as e:  # noqa: BLE001 — per-row report
                    doc = {"error": f"{type(e).__name__}: {e}"}
                    n_err += 1
            out.write(json.dumps(doc, default=str) + "\n")
        window.clear()

    install_sigterm_handler()
    try:
        server.start()
        if server.metrics_http is not None:
            print(f"# metrics: http://127.0.0.1:{server.metrics_http.port}"
                  "/metrics (+ /healthz)", file=sys.stderr)
        for i, row in enumerate(_read_rows(args.input)):
            if not warmed:
                server.start(warmup_row=row)  # non-fatal on a bad row
                warmed = True
            try:
                if binary:
                    chunk.append(row)
                    if len(chunk) >= max(args.max_batch, 1):
                        flush_chunk()
                elif explaining:
                    window.append((i, server.submit_explain_blocking(row)))
                else:
                    window.append((i, server.submit_blocking(row)))
            except KeyError as e:  # strict admission reject
                window.append((i, e))
            n += 1
            if len(window) >= args.queue_capacity:
                drain()
        flush_chunk()
        drain()
    except GracefulShutdown:
        # SIGTERM: stop ADMITTING, but every already-submitted request
        # settles and lands in the output at its slot before exit
        # (rows already read into a pending frame chunk count as
        # submitted — their output lines were promised)
        flush_chunk()
        drain()
        print("# SIGTERM: drained and stopped cleanly", file=sys.stderr)
    finally:
        server.stop()
        if out is not sys.stdout:
            out.close()
        _observability_teardown(args)
    wall = time.monotonic() - t0
    snap = server.snapshot()
    if args.metrics:
        with open(args.metrics, "w") as fh:
            json.dump(snap, fh, indent=2)
    # explained replays flow through the explain lane: its latencies are
    # the ones the operator asked to see
    lat = snap["explain"]["latencyMs"] if explaining else snap["latencyMs"]
    print(f"# served {n} requests ({n_err} errored) in {wall:.2f}s "
          f"({n / max(wall, 1e-9):.0f} rps), p50={lat['p50']}ms "
          f"p95={lat['p95']}ms p99={lat['p99']}ms "
          f"degraded={snap['degraded']['entries']}", file=sys.stderr)
    return 0


def _run_serve_fleet(args: argparse.Namespace, slo=None) -> int:
    """``--model-dir`` mode: many registered models, per-row routing."""
    from transmogrifai_tpu.serving import FleetServer, UnknownModelError

    explaining = args.explain_top_k is not None
    explain_kw = {"explain": True, "explain_top_k": args.explain_top_k} \
        if explaining else {}
    tenancy = None
    if args.tenancy != "off" and (
            args.tenancy == "on"
            or args.model_ram_budget is not None
            or args.tenant_rate is not None
            or args.prewarm_top_k is not None):
        from transmogrifai_tpu.tenancy import TenancyConfig
        tenancy_kw: dict = {}
        if args.model_ram_budget is not None:
            tenancy_kw["ram_budget_bytes"] = args.model_ram_budget
        if args.tenant_rate is not None:
            # 0 disables admission (TenancyConfig treats None/0 alike)
            tenancy_kw["rate_per_s"] = args.tenant_rate or None
        if args.prewarm_top_k is not None:
            tenancy_kw["prewarm_top_k"] = args.prewarm_top_k
        tenancy_kw["precision"] = args.precision
        tenancy = TenancyConfig(**tenancy_kw)
    fleet = FleetServer(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        queue_capacity=args.queue_capacity,
        default_timeout_ms=args.timeout_ms, strict=not args.no_strict,
        route_field=args.model_field,
        metrics_port=args.metrics_port, metrics_host=args.metrics_host,
        access_log_sample=args.access_log_sample, slo=slo,
        tenancy=tenancy, precision=args.precision, **explain_kw)
    entries = fleet.register_dir(args.model_dir)
    if not entries:
        print(f"serve: no saved models (model.json) under "
              f"{args.model_dir!r}", file=sys.stderr)
        return 2
    model_ids = fleet.registry.model_ids()
    default_model = args.default_model
    if default_model is None and len(model_ids) == 1:
        default_model = model_ids[0]
    print(f"# fleet: {len(entries)} version(s) across "
          f"{len(model_ids)} model(s): {', '.join(model_ids)}",
          file=sys.stderr)

    out = sys.stdout if args.output == "-" else open(args.output, "w")
    t0 = time.monotonic()
    n = n_err = 0
    binary = args.wire == "binary"
    window: list[tuple[int, Any]] = []
    #: per-model lanes warm on their first routed row (cf. the
    #: single-model path's first-row warmup; a bad first row only costs
    #: that model lazy compiles). --no-warmup pre-marks every model so
    #: buckets compile lazily, same as the single-model flag
    warmed: set = set(model_ids) if args.no_warmup else set()
    #: --wire binary: contiguous same-model rows awaiting their frame
    #: (flushed at --max-batch or when the routed model id changes —
    #: frames are per-model, output order stays per-line)
    chunk: list = []
    chunk_mid: Optional[str] = None

    def flush_chunk() -> None:
        nonlocal chunk_mid
        if chunk:
            mid = chunk_mid
            window.append((-1, _submit_frame_chunk(
                lambda fr: fleet.submit_frame(mid, fr), mid, chunk[:])))
            chunk.clear()
        chunk_mid = None

    def drain() -> None:
        nonlocal n_err
        for _, item in window:
            if isinstance(item, _FrameChunk):
                for doc in _frame_chunk_docs(item):
                    if doc.get("error") is not None:
                        n_err += 1
                    out.write(json.dumps(doc, default=str) + "\n")
                continue
            if isinstance(item, Exception):
                doc = {"error": f"{type(item).__name__}: {item}"}
                n_err += 1
            else:
                try:
                    doc = item.result()
                except Exception as e:  # noqa: BLE001 — per-row report
                    doc = {"error": f"{type(e).__name__}: {e}"}
                    n_err += 1
            out.write(json.dumps(doc, default=str) + "\n")
        window.clear()

    install_sigterm_handler()
    try:
        fleet.start()
        if fleet.metrics_http is not None:
            print(f"# metrics: http://127.0.0.1:{fleet.metrics_http.port}"
                  "/metrics (+ /healthz, POST /score/<model>)",
                  file=sys.stderr)
        for i, row in enumerate(_read_rows(args.input)):
            mid = row.pop(args.model_field, default_model)
            try:
                if mid is None:
                    raise UnknownModelError(
                        f"row has no {args.model_field!r} key and no "
                        "--default-model is set")
                if mid not in warmed:
                    # pre-compile this model's padding buckets on its
                    # first (known-good-shaped) row; non-fatal
                    lane = fleet.active_lanes().get(mid)
                    if lane is not None:
                        lane.start(warmup_row=dict(row))
                    warmed.add(mid)
                if binary:
                    if chunk and mid != chunk_mid:
                        flush_chunk()
                    chunk_mid = mid
                    chunk.append(row)
                    if len(chunk) >= max(args.max_batch, 1):
                        flush_chunk()
                elif explaining:
                    window.append(
                        (i, fleet.submit_explain_blocking(mid, row)))
                else:
                    window.append((i, fleet.submit_blocking(mid, row)))
            except (KeyError, UnknownModelError) as e:
                # pending frame rows precede this row: flush first so
                # the error line lands at its input slot
                flush_chunk()
                window.append((i, e))
            n += 1
            if len(window) >= args.queue_capacity:
                drain()
        flush_chunk()
        drain()
    except GracefulShutdown:
        flush_chunk()
        drain()
        print("# SIGTERM: drained and stopped cleanly", file=sys.stderr)
    finally:
        # snapshot BEFORE stop: stop() drops the lanes (and their
        # per-model metrics) so a restarted fleet builds fresh ones
        snap = fleet.snapshot()
        fleet.stop()
        if out is not sys.stdout:
            out.close()
        _observability_teardown(args)
    wall = time.monotonic() - t0
    if args.metrics:
        with open(args.metrics, "w") as fh:
            json.dump(snap, fh, indent=2)
    per_model = ", ".join(
        f"{mid}: {doc['requests']['completed']} ok "
        f"p99={doc['latencyMs']['p99']}ms"
        for mid, doc in sorted(snap["models"].items()))
    print(f"# fleet served {n} requests ({n_err} errored) in {wall:.2f}s "
          f"({n / max(wall, 1e-9):.0f} rps) — {per_model}",
          file=sys.stderr)
    return 0
