"""Monoid aggregators: event-level -> entity-level feature rollup.

Parity: reference ``features/.../aggregators/MonoidAggregatorDefaults.scala:
42-120`` (and ``{Numerics,Maps,Geolocation,TimeBasedAggregator}.scala``) —
every feature type has a default monoid used by the aggregate/conditional
readers to roll events grouped by entity key into one value, honoring a
cutoff time and optional look-back window. Same per-type semantics:

  Real/RealNN/Currency sum; Percent mean; Integral sum; Date/DateTime max;
  Binary logical-or; Text family concat; PickList mode; MultiPickList union;
  TextList/DateList concat; Geolocation midpoint; OPVector elementwise sum;
  maps union with the element's monoid (text concat, real sum, percent mean,
  date max, binary or, set union, geo midpoint, prediction mean).

The monoid design is the most TPU-portable idea in the reference: these same
(prepare, combine, present) triples re-appear on-device as pytree psums in
the statistics stages; here they run at host ingest.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Generic, Optional, Sequence, TypeVar

import numpy as np

from transmogrifai_tpu.types import feature_types as ft

__all__ = ["MonoidAggregator", "Event", "FeatureAggregator", "aggregator_of"]

T = TypeVar("T")


@dataclass(frozen=True)
class MonoidAggregator:
    """(prepare, combine, present) with an identity. ``prepare`` maps a raw
    python value (None-able) to the intermediate; ``present`` maps back."""

    name: str
    prepare: Callable[[Any], Any]
    combine: Callable[[Any, Any], Any]
    present: Callable[[Any], Any]
    identity: Any = None

    def reduce(self, values: Sequence[Any]) -> Any:
        acc = self.identity
        for v in values:
            acc = self.combine(acc, self.prepare(v))
        return self.present(acc)


# -- intermediate helpers ----------------------------------------------------

def _keep_none(f):
    """Lift a binary combine over None identities."""
    def g(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return f(a, b)
    return g


def _sum_agg(name):
    return MonoidAggregator(name, lambda v: v,
                            _keep_none(lambda a, b: a + b), lambda x: x)


def _max_agg(name):
    return MonoidAggregator(name, lambda v: v,
                            _keep_none(max), lambda x: x)


def _or_agg(name):
    return MonoidAggregator(name, lambda v: v,
                            _keep_none(lambda a, b: bool(a or b)), lambda x: x)


def _mean_agg(name):
    return MonoidAggregator(
        name,
        prepare=lambda v: None if v is None else (float(v), 1),
        combine=_keep_none(lambda a, b: (a[0] + b[0], a[1] + b[1])),
        present=lambda x: None if x is None else x[0] / x[1])


def _concat_text(name):
    return MonoidAggregator(name, lambda v: v,
                            _keep_none(lambda a, b: a + b), lambda x: x)


def _mode_agg(name):
    """Most frequent value; ties broken by lexicographic order (stable)."""
    def prepare(v):
        return None if v is None else {v: 1}

    def combine(a, b):
        out = dict(a)
        for k, c in b.items():
            out[k] = out.get(k, 0) + c
        return out

    def present(x):
        if not x:
            return None
        return min(x.items(), key=lambda kv: (-kv[1], kv[0]))[0]

    return MonoidAggregator(name, prepare, _keep_none(combine), present)


def _concat_list(name):
    return MonoidAggregator(
        name, lambda v: list(v) if v else None,
        _keep_none(lambda a, b: a + b), lambda x: x if x else [])


def _union_set(name):
    return MonoidAggregator(
        name, lambda v: set(v) if v else None,
        _keep_none(lambda a, b: a | b), lambda x: x if x else set())


def _geo_midpoint(name):
    """Accuracy-weighted midpoint on the unit sphere would be the full
    treatment; the reference uses a cartesian midpoint of lat/lon with max
    accuracy — match that observable behavior."""
    def prepare(v):
        if not v:
            return None
        lat, lon, acc = v
        return (lat, lon, acc, 1)

    def combine(a, b):
        return (a[0] + b[0], a[1] + b[1], max(a[2], b[2]), a[3] + b[3])

    def present(x):
        if x is None:
            return []
        lat, lon, acc, n = x
        return [lat / n, lon / n, acc]

    return MonoidAggregator(name, prepare, _keep_none(combine), present)


def _combine_vector(name):
    def combine(a, b):
        a, b = np.asarray(a), np.asarray(b)
        if a.size == 0:
            return b
        if b.size == 0:
            return a
        if a.shape != b.shape:
            raise ValueError(f"vector aggregation shape mismatch {a.shape} vs {b.shape}")
        return a + b

    return MonoidAggregator(
        name, lambda v: None if v is None or np.asarray(v).size == 0 else np.asarray(v),
        _keep_none(combine), lambda x: x if x is not None else np.zeros(0, np.float32))


def _union_map(name, elem: MonoidAggregator):
    """Union of maps combining same-key values with the element monoid."""
    def prepare(v):
        if not v:
            return None
        return {k: elem.prepare(x) for k, x in v.items()}

    def combine(a, b):
        out = dict(a)
        for k, x in b.items():
            out[k] = elem.combine(out.get(k), x)
        return out

    def present(x):
        if x is None:
            return {}
        return {k: elem.present(v) for k, v in x.items()}

    return MonoidAggregator(name, prepare, _keep_none(combine), present)


# -- dispatch (mirrors MonoidAggregatorDefaults.aggregatorOf) ---------------

def aggregator_of(ftype: type[ft.FeatureType]) -> MonoidAggregator:
    t = ft
    concat = _concat_text
    table: dict[type, Callable[[], MonoidAggregator]] = {
        t.OPVector: lambda: _combine_vector("CombineVector"),
        # lists
        t.TextList: lambda: _concat_list("ConcatTextList"),
        t.DateList: lambda: _concat_list("ConcatDateList"),
        t.DateTimeList: lambda: _concat_list("ConcatDateTimeList"),
        t.Geolocation: lambda: _geo_midpoint("GeolocationMidpoint"),
        # numerics
        t.Binary: lambda: _or_agg("LogicalOr"),
        t.Currency: lambda: _sum_agg("SumCurrency"),
        t.DateTime: lambda: _max_agg("MaxDateTime"),
        t.Date: lambda: _max_agg("MaxDate"),
        t.Integral: lambda: _sum_agg("SumIntegral"),
        t.Percent: lambda: _mean_agg("MeanPercent"),
        # RealNN is non-nullable: empty aggregation presents as 0.0
        # (reference SumRealNN's monoid zero)
        t.RealNN: lambda: MonoidAggregator(
            "SumRealNN", lambda v: v, _keep_none(lambda a, b: a + b),
            lambda x: 0.0 if x is None else x),
        t.Real: lambda: _sum_agg("SumReal"),
        # sets
        t.MultiPickList: lambda: _union_set("UnionMultiPickList"),
        # text
        t.PickList: lambda: _mode_agg("ModePickList"),
        t.Base64: lambda: concat("ConcatBase64"),
        t.ComboBox: lambda: concat("ConcatComboBox"),
        t.Email: lambda: concat("ConcatEmail"),
        t.ID: lambda: concat("ConcatID"),
        t.Phone: lambda: concat("ConcatPhone"),
        t.TextArea: lambda: concat("ConcatTextArea"),
        t.Country: lambda: concat("ConcatCountry"),
        t.State: lambda: concat("ConcatState"),
        t.City: lambda: concat("ConcatCity"),
        t.PostalCode: lambda: concat("ConcatPostalCode"),
        t.Street: lambda: concat("ConcatStreet"),
        t.Text: lambda: concat("ConcatText"),
        # maps
        t.BinaryMap: lambda: _union_map("UnionBinaryMap", _or_agg("or")),
        t.CurrencyMap: lambda: _union_map("UnionCurrencyMap", _sum_agg("sum")),
        t.DateTimeMap: lambda: _union_map("UnionMaxDateTimeMap", _max_agg("max")),
        t.DateMap: lambda: _union_map("UnionMaxDateMap", _max_agg("max")),
        t.IntegralMap: lambda: _union_map("UnionIntegralMap", _sum_agg("sum")),
        t.MultiPickListMap: lambda: _union_map("UnionMultiPickListMap",
                                               _union_set("union")),
        t.PercentMap: lambda: _union_map("UnionMeanPercentMap", _mean_agg("mean")),
        t.RealMap: lambda: _union_map("UnionRealMap", _sum_agg("sum")),
        t.GeolocationMap: lambda: _union_map("UnionGeolocationMidpointMap",
                                             _geo_midpoint("mid")),
        t.Prediction: lambda: _union_map("UnionMeanPrediction", _mean_agg("mean")),
        t.NameStats: lambda: _union_map("UnionConcatNameStats", concat("concat")),
    }
    # text-valued maps share union-concat
    for cls in (t.Base64Map, t.ComboBoxMap, t.EmailMap, t.IDMap, t.PhoneMap,
                t.PickListMap, t.TextAreaMap, t.TextMap, t.URLMap, t.CountryMap,
                t.StateMap, t.CityMap, t.PostalCodeMap, t.StreetMap):
        table.setdefault(cls, lambda c=cls: _union_map(
            f"UnionConcat{c.__name__}", concat("concat")))

    # exact match first, then walk the mro (Currency before Real etc. is
    # guaranteed because dict lookup is exact)
    if ftype in table:
        return table[ftype]()
    for base in ftype.__mro__:
        if base in table:
            return table[base]()
    raise KeyError(f"No default aggregator for {ftype.__name__}")


# -- event-level application -------------------------------------------------

@dataclass(frozen=True)
class Event(Generic[T]):
    """A timestamped raw value for one entity (reference aggregators.Event)."""
    time: int
    value: Any


class FeatureAggregator:
    """Applies a monoid aggregator to an entity's events honoring time
    semantics (reference ``aggregators/FeatureAggregator.scala:108-125``
    ``filterByDateWithCutoff`` — boundaries match it exactly):

    - predictors aggregate events with ``time < cutoff`` (and
      ``time >= cutoff - window_ms`` when a window is set)
    - responses aggregate events with ``time >= cutoff`` (and
      ``time <= cutoff + window_ms`` when a window is set)
    """

    def __init__(self, aggregator: MonoidAggregator,
                 is_response: bool = False,
                 window_ms: Optional[int] = None):
        self.aggregator = aggregator
        self.is_response = is_response
        self.window_ms = window_ms

    def extract(self, events: Sequence[Event],
                cutoff_ms: Optional[int] = None) -> Any:
        vals = []
        for e in events:
            if cutoff_ms is not None:
                if self.is_response:
                    if e.time < cutoff_ms:
                        continue
                    if self.window_ms is not None and e.time > cutoff_ms + self.window_ms:
                        continue
                else:
                    if e.time >= cutoff_ms:
                        continue
                    if self.window_ms is not None and e.time < cutoff_ms - self.window_ms:
                        continue
            vals.append(e.value)
        return self.aggregator.reduce(vals)
