from transmogrifai_tpu.aggregators.monoid import (
    Event, FeatureAggregator, MonoidAggregator, aggregator_of,
)

__all__ = ["Event", "FeatureAggregator", "MonoidAggregator", "aggregator_of"]
