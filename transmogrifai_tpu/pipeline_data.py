"""PipelineData: the mixed host/device view stages execute against.

The analog of the raw + intermediate Spark DataFrame flowing through
``FitStagesUtil``: a HostFrame of ingested columns plus device-resident
columns produced by fused stage programs. Columns convert lazily between
residencies:

- numeric host columns  -> ``NumericColumn`` (f32 values + f32 mask)
- text-ish host columns -> ``CodesColumn`` (dictionary-encoded on first use)
- vector host columns   -> ``VectorColumn``
- device outputs pull back to host only at the edges (save/inspect/local).

When a mesh is active, device placement shards the row axis over the "data"
axis, padding non-divisible row counts up to the mesh multiple (padded slots
carry mask=0 / code=-1 so masked statistics ignore them; ``row_mask`` exposes
the validity vector and host pulls slice the padding back off).
"""

from __future__ import annotations

import functools
from typing import Any, Iterable, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import frame as fr
from transmogrifai_tpu.parallel import mesh as pmesh
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils.dict_encode import dict_encode

__all__ = ["PipelineData"]


def _shard(arr, pad_value=0.0):
    return pmesh.pad_and_shard_rows(arr, pad_value=pad_value)


@functools.partial(jax.jit, donate_argnums=(0,))
def _fill_rows(buf, chunk, start):
    return jax.lax.dynamic_update_slice(
        buf, chunk, (start,) + (0,) * (buf.ndim - 1))


def _upload_rows(arr):
    """Host->device transfer in bounded row chunks.

    Tunneled TPU workers have crashed ("TPU worker process crashed or
    restarted") on ~1 GB implicit argument uploads; explicit device_put
    of <=TRANSMOGRIFAI_UPLOAD_CHUNK_MB row slices keeps each transfer
    small. Chunks are written into one preallocated (donated) device
    buffer so peak device memory stays ~1x the array, not 2x. No-op for
    small arrays and for already-device arrays."""
    import os
    if not isinstance(arr, np.ndarray):
        return arr
    chunk_bytes = int(os.environ.get(
        "TRANSMOGRIFAI_UPLOAD_CHUNK_MB", 96)) << 20
    if arr.nbytes <= chunk_bytes or arr.ndim == 0 or arr.shape[0] == 0:
        return jax.device_put(arr)
    per_row = max(arr.nbytes // arr.shape[0], 1)
    rows_per = max(int(chunk_bytes // per_row), 1)
    out = jnp.zeros(arr.shape, arr.dtype)
    for i in range(0, arr.shape[0], rows_per):
        out = _fill_rows(out, jax.device_put(arr[i:i + rows_per]),
                         jnp.int32(i))
    return out


@jax.jit
def _split_columns(dvals, dmasks):
    k = dvals.shape[1]
    dmasks = dmasks.astype(jnp.float32)
    return (tuple(dvals[:, i] for i in range(k)),
            tuple(dmasks[:, i] for i in range(k)))


class PipelineData:
    def __init__(self, host: fr.HostFrame,
                 device: Optional[Mapping[str, Any]] = None,
                 n_rows_logical: Optional[int] = None):
        self.host = host
        self.device: dict[str, Any] = dict(device or {})
        self._codes_cache: dict[str, fr.CodesColumn] = {}
        #: true (unpadded) row count; device columns may carry mesh padding
        self._n_logical = n_rows_logical if n_rows_logical is not None \
            else (host.n_rows or None)
        self._row_mask = None

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_host(host: fr.HostFrame) -> "PipelineData":
        return PipelineData(host)

    @property
    def n_rows(self) -> int:
        if self._n_logical is not None:
            return self._n_logical
        if self.host.n_rows:
            return self.host.n_rows
        for c in self.device.values():
            v = getattr(c, "values", getattr(c, "codes", None))
            if v is not None:
                return int(v.shape[0])
        return 0

    def row_mask(self) -> jnp.ndarray:
        """Device validity vector over the (possibly padded) row axis:
        1.0 for real rows, 0.0 for mesh-padding slots. Statistics stages
        weight by this so padded rows contribute monoid identity."""
        if self._row_mask is None:
            n = self.n_rows
            ctx = pmesh.current_mesh()
            n_pad = pmesh.pad_rows(n) if ctx is not None else n
            mask = np.zeros(n_pad, np.float32)
            mask[:n] = 1.0
            self._row_mask = _shard(jnp.asarray(mask))
        return self._row_mask

    def has(self, name: str) -> bool:
        return name in self.device or name in self.host

    # -- column access -------------------------------------------------------
    def host_col(self, name: str) -> fr.HostColumn:
        if name in self.host:
            return self.host[name]
        if name in self.device:
            return self._device_to_host(self.device[name])
        raise KeyError(f"No column {name!r}")

    def device_col(self, name: str) -> Any:
        if name in self.device:
            return self.device[name]
        if name in self._codes_cache:
            return self._codes_cache[name]
        if name not in self.host:
            raise KeyError(f"No column {name!r}")
        col = self.host[name]
        kind = col.kind
        if kind in fr.NUMERIC_KINDS:
            # bulk path: move EVERY numeric host column in two transfers
            # (one [n,k] values matrix + one mask matrix) instead of 2k
            # small ones — host->device latency, not bandwidth, dominates
            # on tunneled/remote devices
            self._bulk_upload_numeric()
            return self.device[name]
        if kind == "vector":
            # same chunked-transfer discipline as the numeric bulk path
            # (wide pre-vectorized matrices are the other >GB upload);
            # the mesh path still places in one transfer — chunked
            # SHARDED placement is future work, and multi-chip meshes on
            # this rig are CPU-virtual (no tunnel) anyway
            vals = np.asarray(col.values, np.float32)
            dval = _shard(vals) if pmesh.current_mesh() is not None \
                else _upload_rows(vals)
            dev = fr.VectorColumn(dval, col.meta)
            self.device[name] = dev
            return dev
        if kind in fr.TEXT_KINDS:
            dev = self._encode_text(col)
            self._codes_cache[name] = dev
            return dev
        raise TypeError(
            f"Column {name!r} of kind {kind!r} has no generic device "
            "representation; the consuming stage must handle it on host")

    def _bulk_upload_numeric(self) -> None:
        pending = [(n, c) for n, c in self.host.columns.items()
                   if c.kind in fr.NUMERIC_KINDS and n not in self.device]
        if not pending:
            return
        from transmogrifai_tpu.utils.profiling import OpStep, profiler
        with profiler.phase(OpStep.DATA_READING_AND_FILTERING):
            vals = np.stack(
                [np.where(c.mask, c.values, 0.0).astype(np.float32)
                 for _, c in pending], axis=1)
            # masks travel as uint8 (4x fewer bytes over the tunnel) and
            # widen to f32 on device inside _split_columns
            masks = np.stack([c.mask.astype(np.uint8) for _, c in pending],
                             axis=1)
            if pmesh.current_mesh() is not None:
                dvals = _shard(vals)
                dmasks = _shard(masks)
            else:
                dvals = _upload_rows(vals)
                dmasks = _upload_rows(masks)
            # split into per-column arrays inside ONE jitted program — k
            # eager `dvals[:, i]` slices would pay k dispatch round-trips
            # each on tunneled/remote devices (measured ~14s for 28 columns
            # at 1M rows)
            cols_v, cols_m = _split_columns(dvals, dmasks)
            for i, (name, _) in enumerate(pending):
                self.device[name] = fr.NumericColumn(cols_v[i], cols_m[i])

    @staticmethod
    def _encode_text(col: fr.HostColumn) -> fr.CodesColumn:
        codes, vocab = dict_encode(col.values)
        return fr.CodesColumn(_shard(codes, pad_value=-1), tuple(vocab))

    def _device_to_host(self, col: Any) -> fr.HostColumn:
        n = self.n_rows  # slice mesh padding back off on host pull
        if isinstance(col, fr.NumericColumn):
            vals = np.asarray(col.values, dtype=np.float64)[:n]
            mask = (np.asarray(col.mask) > 0.5)[:n]
            return fr.HostColumn(ft.Real, vals, mask)
        if isinstance(col, fr.VectorColumn):
            return fr.HostColumn(ft.OPVector,
                                 np.asarray(col.values, np.float32)[:n],
                                 meta=col.metadata)
        if isinstance(col, fr.CodesColumn):
            codes = np.asarray(col.codes)[:n]
            vals = np.empty(codes.shape[0], dtype=object)
            for i, c in enumerate(codes):
                vals[i] = col.vocab[c] if c >= 0 else None
            return fr.HostColumn(ft.Text, vals)
        if isinstance(col, fr.PredictionColumn):
            pred = np.asarray(col.prediction, np.float64)[:n]
            raw = np.asarray(col.raw_prediction, np.float64)[:n]
            prob = np.asarray(col.probability, np.float64)[:n]
            vals = np.empty(pred.shape[0], dtype=object)
            for i in range(pred.shape[0]):
                vals[i] = ft.Prediction.make(pred[i], raw[i], prob[i]).value
            return fr.HostColumn(ft.Prediction, vals)
        raise TypeError(f"Cannot pull {type(col).__name__} to host")

    # -- updates -------------------------------------------------------------
    def with_host_cols(self, new: Mapping[str, fr.HostColumn]) -> "PipelineData":
        return PipelineData(self.host.with_columns(new), self.device,
                            n_rows_logical=self._n_logical)

    def with_device_cols(self, new: Mapping[str, Any]) -> "PipelineData":
        dev = dict(self.device)
        dev.update(new)
        out = PipelineData(self.host, dev, n_rows_logical=self._n_logical)
        out._codes_cache = self._codes_cache
        out._row_mask = self._row_mask
        return out

    def select_result(self, names: Iterable[str]) -> "PipelineData":
        names = list(names)
        host_cols = {n: self.host[n] for n in names if n in self.host}
        dev_cols = {n: self.device[n] for n in names if n in self.device}
        return PipelineData(fr.HostFrame(host_cols, self.host.key), dev_cols,
                            n_rows_logical=self._n_logical)

    # -- row-axis ops (splits) ----------------------------------------------
    def take(self, idx: np.ndarray) -> "PipelineData":
        host = self.host.take(idx) if self.host.names() else self.host
        jidx = jnp.asarray(np.asarray(idx))
        # re-pad + re-shard the gathered rows so fold subsets keep the mesh
        # invariant (device length == pad_rows(logical), mask 0 on padding) —
        # row_mask() of the subset must match its device columns' length
        dev = {}
        for n, c in self.device.items():
            if isinstance(c, fr.NumericColumn):
                dev[n] = fr.NumericColumn(_shard(c.values[jidx]),
                                          _shard(c.mask[jidx]))
            elif isinstance(c, fr.VectorColumn):
                dev[n] = fr.VectorColumn(_shard(c.values[jidx]), c.metadata)
            elif isinstance(c, fr.CodesColumn):
                dev[n] = fr.CodesColumn(_shard(c.codes[jidx], pad_value=-1),
                                        c.vocab)
            elif isinstance(c, fr.PredictionColumn):
                dev[n] = fr.PredictionColumn(
                    _shard(c.prediction[jidx]), _shard(c.raw_prediction[jidx]),
                    _shard(c.probability[jidx]))
            else:
                raise TypeError(f"take: unsupported device column {type(c)}")
        if self.host.names():
            return PipelineData(host, dev, n_rows_logical=len(idx))
        return PipelineData(fr.HostFrame({}, None), dev,
                            n_rows_logical=len(idx))

    def vector_meta(self, name: str):
        col = self.device.get(name)
        return getattr(col, "metadata", None)
