"""Feature-vector provenance metadata.

Parity: reference ``features/.../utils/spark/OpVectorMetadata.scala`` and
``OpVectorColumnMetadata.scala`` — every column of every feature vector knows
its parent feature(s), grouping (e.g. map key or pivot group), indicator value
(pivot category), descriptor (e.g. unit-circle component) and whether it is a
null-indicator. The reference rides this on DataFrame column Metadata; here it
is static aux data on ``VectorColumn`` pytrees, preserved through jit.

This is load-bearing: SanityChecker's per-group stats, ModelInsights'
per-derived-column report and LOCO's hash-group aggregation all key off it.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

__all__ = ["VectorColumnMetadata", "VectorMetadata", "NULL_INDICATOR",
           "OTHER", "parent_of"]


def parent_of(feature) -> tuple[tuple[str, ...], tuple[str, ...]]:
    """(parent names, parent type names) for metadata: the raw ancestors of
    a derived feature (so provenance reaches the original columns), falling
    back to the feature itself when it is raw."""
    raws = feature.raw_features()
    if raws:
        return (tuple(r.name for r in raws),
                tuple(r.ftype.__name__ for r in raws))
    return (feature.name,), (feature.ftype.__name__,)

#: indicator value used for null-tracking columns (reference NullString)
NULL_INDICATOR = "NullIndicatorValue"
#: pivot bucket for values outside topK (reference OtherString)
OTHER = "OTHER"


@dataclass(frozen=True)
class VectorColumnMetadata:
    """Provenance of one column in a feature vector."""

    parent_feature: tuple[str, ...]            # raw/derived parent feature names
    parent_feature_type: tuple[str, ...]       # their FeatureType class names
    grouping: Optional[str] = None             # pivot group / map key
    indicator_value: Optional[str] = None      # pivot category value
    descriptor_value: Optional[str] = None     # e.g. "sin_HourOfDay"
    index: int = 0                             # position in the combined vector
    #: name of the derived feature whose lineage produced THIS column (the
    #: key into VectorMetadata.history) — set by VectorsCombiner so sibling
    #: blocks over the same raw feature don't cross-attribute their stages
    parent_chain: Optional[str] = None

    @property
    def is_null_indicator(self) -> bool:
        return self.indicator_value == NULL_INDICATOR

    @property
    def is_other_indicator(self) -> bool:
        return self.indicator_value == OTHER

    def make_col_name(self) -> str:
        """Human-readable column name (reference makeColName)."""
        parts = list(self.parent_feature)
        if self.grouping and self.grouping not in parts:
            parts.append(self.grouping)
        tail = self.indicator_value or self.descriptor_value
        if tail:
            parts.append(tail)
        return "_".join(parts) + f"_{self.index}"

    def feature_group(self) -> Optional[str]:
        """Grouping key for correlated-removal and LOCO aggregation: columns
        sharing (parent, grouping) form one categorical/hash group."""
        if self.grouping is not None:
            return f"{'_'.join(self.parent_feature)}::{self.grouping}"
        if self.indicator_value is not None:
            return "_".join(self.parent_feature)
        return None

    def to_json(self) -> dict:
        out = {
            "parentFeature": list(self.parent_feature),
            "parentFeatureType": list(self.parent_feature_type),
            "grouping": self.grouping,
            "indicatorValue": self.indicator_value,
            "descriptorValue": self.descriptor_value,
            "index": self.index,
        }
        if self.parent_chain is not None:
            out["parentChain"] = self.parent_chain
        return out

    @staticmethod
    def from_json(d: dict) -> "VectorColumnMetadata":
        return VectorColumnMetadata(
            parent_feature=tuple(d["parentFeature"]),
            parent_feature_type=tuple(d["parentFeatureType"]),
            grouping=d.get("grouping"),
            indicator_value=d.get("indicatorValue"),
            descriptor_value=d.get("descriptorValue"),
            index=int(d.get("index", 0)),
            parent_chain=d.get("parentChain"),
        )


#: one vector-level lineage entry: (feature name, origin raw features,
#: stage operation names along the chain raw -> feature) — the analog of
#: reference ``FeatureHistory`` values in ``OpVectorMetadata.history``
HistoryEntry = tuple[str, tuple[str, ...], tuple[str, ...]]


@dataclass(frozen=True)
class VectorMetadata:
    """Metadata for a whole feature vector: ordered column provenance.

    ``history`` is the reference's ``Map[String, FeatureHistory]``
    (``OpVectorMetadata.scala:216-277``) as a hashable tuple: one entry per
    contributing (possibly derived) feature, carrying its origin raw
    features and the operation names of every stage between them. Kept at
    the vector level and merged per column by :meth:`column_history` — the
    ``getColumnHistory``/``OpVectorColumnHistory`` analog."""

    name: str
    columns: tuple[VectorColumnMetadata, ...] = field(default_factory=tuple)
    history: tuple[HistoryEntry, ...] = ()

    @property
    def size(self) -> int:
        return len(self.columns)

    def col_names(self) -> list[str]:
        return [c.make_col_name() for c in self.columns]

    @staticmethod
    def history_of(features: Sequence) -> tuple[HistoryEntry, ...]:
        """Lineage entries for the given FeatureLike objects (their
        ``history()`` already walks the raw->derived stage chain)."""
        entries = []
        for f in features:
            try:
                h = f.history()
            except Exception:  # failure-ok: feature without history is skipped
                continue
            entries.append((f.name, tuple(h["originFeatures"]),
                            tuple(h["stages"])))
        return tuple(entries)

    def with_history(self, entries: Sequence[HistoryEntry]) -> "VectorMetadata":
        return VectorMetadata(self.name, self.columns, tuple(entries))

    def column_history(self) -> list[dict]:
        """Per-column lineage (reference ``getColumnHistory()``): a column
        tagged with its producing chain (``parent_chain``, set by the
        combiner) reports exactly that entry's raw->derived stage chain;
        untagged columns fall back to joining the entries whose origins
        intersect their raw parents."""
        by_name = {name: (origins, stages)
                   for name, origins, stages in self.history}
        out = []
        for c in self.columns:
            parents = set(c.parent_feature)
            origins: set[str] = set()
            stages: set[str] = set()
            if c.parent_chain is not None and c.parent_chain in by_name:
                ent_origins, ent_stages = by_name[c.parent_chain]
                origins.update(ent_origins)
                stages.update(ent_stages)
            else:
                for name, ent_origins, ent_stages in self.history:
                    if name in parents or parents & set(ent_origins):
                        origins.update(ent_origins)
                        stages.update(ent_stages)
            out.append({
                "columnName": c.make_col_name(),
                "parentFeatureName": list(c.parent_feature),
                "parentFeatureOrigins": sorted(origins or parents),
                "parentFeatureStages": sorted(stages),
                "parentFeatureType": list(c.parent_feature_type),
                "grouping": c.grouping,
                "indicatorValue": c.indicator_value,
                "descriptorValue": c.descriptor_value,
                "index": c.index,
            })
        return out

    def reindexed(self, start: int = 0) -> "VectorMetadata":
        cols = tuple(replace(c, index=start + i) for i, c in enumerate(self.columns))
        return VectorMetadata(self.name, cols, self.history)

    @staticmethod
    def flatten(name: str, metas: Sequence["VectorMetadata"]) -> "VectorMetadata":
        """Concatenate vector metadatas (reference OpVectorMetadata.flatten),
        reassigning global column indices and merging lineage maps."""
        cols: list[VectorColumnMetadata] = []
        hist: list[HistoryEntry] = []
        seen: set[str] = set()
        for m in metas:
            cols.extend(m.columns)
            for e in m.history:
                if e[0] not in seen:
                    seen.add(e[0])
                    hist.append(e)
        out = VectorMetadata(name, tuple(cols), tuple(hist)).reindexed(0)
        return out

    def select(self, keep: Sequence[int]) -> "VectorMetadata":
        """Keep a subset of columns (DropIndices rewiring), reindexed."""
        cols = tuple(self.columns[i] for i in keep)
        return VectorMetadata(self.name, cols, self.history).reindexed(0)

    def to_json(self) -> dict:
        return {"name": self.name,
                "columns": [c.to_json() for c in self.columns],
                "history": [{"feature": n, "originFeatures": list(o),
                             "stages": list(s)} for n, o, s in self.history]}

    @staticmethod
    def from_json(d: dict) -> "VectorMetadata":
        return VectorMetadata(
            d["name"],
            tuple(VectorColumnMetadata.from_json(c) for c in d.get("columns", [])),
            tuple((h["feature"], tuple(h.get("originFeatures", ())),
                   tuple(h.get("stages", ())))
                  for h in d.get("history", ())),
        )
