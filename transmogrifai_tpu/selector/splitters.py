"""Data splitters: holdout reservation, class balancing, label cutting.

Parity: reference ``core/.../stages/impl/tuning/{DataSplitter,DataBalancer,
DataCutter}.scala`` —

- **DataSplitter**: reserve a test/holdout fraction (+ max training rows cap).
- **DataBalancer** (binary): when the positive class is rarer than
  ``sample_fraction``, down-sample the majority so positives reach that
  fraction (keeping the sample fractions in a summary for metadata).
- **DataCutter** (multiclass): keep at most ``max_label_categories`` labels /
  drop labels rarer than ``min_label_fraction``; re-index kept labels.

All operate on index arrays over device-resident (X, y, w) triples; the
actual gather happens once on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

__all__ = ["SplitterSummary", "DataSplitter", "DataBalancer", "DataCutter"]


@dataclass
class SplitterSummary:
    splitter: str = ""
    detail: dict = field(default_factory=dict)


class DataSplitter:
    """Random train/holdout reservation."""

    #: does prepare_indices need the label values on host?
    requires_label = False

    def __init__(self, reserve_test_fraction: float = 0.1, seed: int = 42,
                 max_training_sample: Optional[int] = None):
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed
        self.max_training_sample = max_training_sample
        self.summary: Optional[SplitterSummary] = None

    def split_indices(self, n: int, y: Optional[np.ndarray] = None
                      ) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        test, train = perm[:n_test], perm[n_test:]
        if self.max_training_sample and train.size > self.max_training_sample:
            train = train[:self.max_training_sample]
        self.summary = SplitterSummary(
            "DataSplitter", {"trainRows": int(train.size),
                             "testRows": int(test.size)})
        return np.sort(train), np.sort(test)

    # balancing hook applied to the *training* portion only
    def prepare_indices(self, train_idx: np.ndarray, y: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (possibly resampled train indices, sample weights)."""
        return train_idx, np.ones(train_idx.size, dtype=np.float32)


class DataBalancer(DataSplitter):
    """Binary down-sampler toward a target positive fraction."""

    requires_label = True

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: Optional[int] = 1_000_000,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed, max_training_sample)
        self.sample_fraction = sample_fraction

    def prepare_indices(self, train_idx, y):
        rng = np.random.default_rng(self.seed + 1)
        yt = y[train_idx]
        pos = train_idx[yt >= 0.5]
        neg = train_idx[yt < 0.5]
        n_pos, n_neg = pos.size, neg.size
        small, big = (pos, neg) if n_pos <= n_neg else (neg, pos)
        frac = small.size / max(train_idx.size, 1)
        if frac >= self.sample_fraction or small.size == 0:
            self.summary = SplitterSummary(
                "DataBalancer", {"balanced": False,
                                 "positiveFraction": n_pos / max(train_idx.size, 1)})
            return train_idx, np.ones(train_idx.size, dtype=np.float32)
        # down-sample the majority so the minority reaches sample_fraction
        target_big = int(small.size * (1.0 - self.sample_fraction)
                         / self.sample_fraction)
        keep_big = rng.choice(big, size=min(target_big, big.size), replace=False)
        out = np.sort(np.concatenate([small, keep_big]))
        self.summary = SplitterSummary(
            "DataBalancer",
            {"balanced": True,
             "downSampleFraction": keep_big.size / max(big.size, 1),
             "positiveFraction": n_pos / max(train_idx.size, 1),
             "keptRows": int(out.size)})
        return out, np.ones(out.size, dtype=np.float32)


class DataCutter(DataSplitter):
    """Multiclass label trimming: keep the most frequent labels."""

    requires_label = True

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction
        self.kept_labels: Optional[list[float]] = None

    def prepare_indices(self, train_idx, y):
        yt = y[train_idx]
        labels, counts = np.unique(yt, return_counts=True)
        frac = counts / max(yt.size, 1)
        keep = labels[(frac >= self.min_label_fraction)]
        if keep.size > self.max_label_categories:
            order = np.argsort(-counts)
            keep = labels[order[:self.max_label_categories]]
        self.kept_labels = sorted(float(l) for l in keep)
        mask = np.isin(yt, keep)
        out = train_idx[mask]
        self.summary = SplitterSummary(
            "DataCutter", {"labelsKept": len(self.kept_labels),
                           "labelsDropped": int(labels.size - keep.size)})
        return out, np.ones(out.size, dtype=np.float32)
