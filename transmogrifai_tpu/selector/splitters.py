"""Data splitters: holdout reservation, class balancing, label cutting.

Parity: reference ``core/.../stages/impl/tuning/{DataSplitter,DataBalancer,
DataCutter}.scala`` —

- **DataSplitter**: reserve a test/holdout fraction (+ max training rows cap).
- **DataBalancer** (binary): when the positive class is rarer than
  ``sample_fraction``, down-sample the majority so positives reach that
  fraction (keeping the sample fractions in a summary for metadata).
- **DataCutter** (multiclass): keep at most ``max_label_categories`` labels /
  drop labels rarer than ``min_label_fraction``; re-index kept labels.

All operate on index arrays over device-resident (X, y, w) triples; the
actual gather happens once on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

__all__ = ["SplitterSummary", "DataSplitter", "DataBalancer", "DataCutter"]


@dataclass
class SplitterSummary:
    splitter: str = ""
    detail: dict = field(default_factory=dict)


class DataSplitter:
    """Random train/holdout reservation."""

    #: does prepare_indices need the label values on host?
    requires_label = False

    def __init__(self, reserve_test_fraction: float = 0.1, seed: int = 42,
                 max_training_sample: Optional[int] = None):
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed
        self.max_training_sample = max_training_sample
        self.summary: Optional[SplitterSummary] = None

    #: rows kept by split_indices; balancers return None here because they
    #: apply the cap through sampling fractions instead of truncation
    @property
    def _truncation_cap(self) -> Optional[int]:
        return self.max_training_sample

    def split_indices(self, n: int, y: Optional[np.ndarray] = None
                      ) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        test, train = perm[:n_test], perm[n_test:]
        cap = self._truncation_cap
        if cap and train.size > cap:
            train = train[:cap]
        self.summary = SplitterSummary(
            "DataSplitter", {"trainRows": int(train.size),
                             "testRows": int(test.size)})
        return np.sort(train), np.sort(test)

    # balancing hook applied to the *training* portion only
    def prepare_indices(self, train_idx: np.ndarray, y: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        """Returns (possibly resampled train indices, sample weights)."""
        return train_idx, np.ones(train_idx.size, dtype=np.float32)


class DataBalancer(DataSplitter):
    """Binary re-balancer toward a target minority fraction.

    Parity: reference ``DataBalancer.scala:76-113`` (``getProportions``
    computes BOTH the up-sample multiplier for the minority and the
    down-sample fraction for the majority), ``:208-247`` (``estimate``:
    already-balanced data is only stratified-down-sampled when it exceeds
    ``maxTrainingSample``) and ``:279-318`` (``rebalance`` up-samples WITH
    replacement when the multiplier > 1, keeps the minority whole at 1,
    down-samples it without replacement below 1). Summary metadata mirrors
    ``DataBalancerSummary`` (positiveLabels/negativeLabels/desiredFraction/
    upSamplingFraction/downSamplingFraction).

    ``max_training_sample`` participates in the proportion math (as in the
    reference) instead of truncating the training set up front, so the
    base-class cap is intentionally not applied here.
    """

    requires_label = True

    #: shadows the base property: no up-front truncation (the cap acts
    #: through get_proportions / the already-balanced fraction instead)
    _truncation_cap = None

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: Optional[int] = 1_000_000,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed, max_training_sample)
        self.sample_fraction = sample_fraction

    @staticmethod
    def get_proportions(small_count: float, big_count: float, sample_f: float,
                        max_training_sample: int) -> tuple[float, float]:
        """(downSample fraction for big, upSample multiplier for small) —
        reference DataBalancer.scala:84-115."""
        def up_ok(m: int) -> bool:
            return (m * small_count * (1.0 - sample_f) < sample_f * big_count
                    and max_training_sample * sample_f > small_count * m)

        if small_count < max_training_sample * sample_f:
            up = next((float(m) for m in (100, 50, 10, 5, 4, 3, 2)
                       if up_ok(m)), 1.0)
            down = (small_count * up / sample_f - small_count * up) / big_count
            return down, up
        # minority alone already exceeds the cap: shrink both classes
        up = (max_training_sample * sample_f) / small_count
        down = (1.0 - sample_f) * max_training_sample / big_count
        return down, up

    def prepare_indices(self, train_idx, y):
        rng = np.random.default_rng(self.seed + 1)
        yt = y[train_idx]
        pos = train_idx[yt >= 0.5]
        neg = train_idx[yt < 0.5]
        n_pos, n_neg = pos.size, neg.size
        total = max(train_idx.size, 1)
        is_pos_small = n_pos < n_neg
        small, big = (pos, neg) if is_pos_small else (neg, pos)
        f = self.sample_fraction
        max_train = self.max_training_sample or total

        def summarize(up: float, down: float, kept: int, balanced: bool):
            self.summary = SplitterSummary(
                "DataBalancer",
                {"balanced": balanced,
                 "positiveLabels": int(n_pos), "negativeLabels": int(n_neg),
                 "desiredFraction": f,
                 "upSamplingFraction": up, "downSamplingFraction": down,
                 "positiveFraction": n_pos / total, "keptRows": int(kept)})

        def take(idx: np.ndarray, fraction: float) -> np.ndarray:
            if fraction >= 1.0:
                return idx
            n = int(round(idx.size * fraction))
            return rng.choice(idx, size=min(n, idx.size), replace=False)

        if small.size == 0 or small.size / total >= f:
            # already balanced (estimate:225-234): stratified down-sample
            # only when the data exceeds the training cap
            fraction = max_train / total if max_train < total else 1.0
            if fraction >= 1.0:
                out = train_idx
            else:
                out = np.concatenate([take(neg, fraction), take(pos, fraction)])
            summarize(up=0.0, down=fraction, kept=out.size, balanced=False)
            return np.sort(out), np.ones(out.size, dtype=np.float32)

        down, up = self.get_proportions(small.size, big.size, f, max_train)
        big_keep = take(big, down)
        if up > 1.0:
            # rebalance:288 — sample WITH replacement at the multiplier
            small_keep = rng.choice(small, size=int(round(small.size * up)),
                                    replace=True)
        elif up == 1.0:
            small_keep = small
        else:
            small_keep = take(small, up)
        out = np.sort(np.concatenate([small_keep, big_keep]))
        summarize(up=up, down=down, kept=out.size, balanced=True)
        return out, np.ones(out.size, dtype=np.float32)


class DataCutter(DataSplitter):
    """Multiclass label trimming: keep the most frequent labels."""

    requires_label = True

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0,
                 reserve_test_fraction: float = 0.1, seed: int = 42):
        super().__init__(reserve_test_fraction, seed)
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction
        self.kept_labels: Optional[list[float]] = None

    def prepare_indices(self, train_idx, y):
        yt = y[train_idx]
        labels, counts = np.unique(yt, return_counts=True)
        frac = counts / max(yt.size, 1)
        keep = labels[(frac >= self.min_label_fraction)]
        if keep.size > self.max_label_categories:
            order = np.argsort(-counts)
            keep = labels[order[:self.max_label_categories]]
        self.kept_labels = sorted(float(l) for l in keep)
        mask = np.isin(yt, keep)
        out = train_idx[mask]
        self.summary = SplitterSummary(
            "DataCutter", {"labelsKept": len(self.kept_labels),
                           "labelsDropped": int(labels.size - keep.size)})
        return out, np.ones(out.size, dtype=np.float32)
