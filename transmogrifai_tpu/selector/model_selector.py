"""ModelSelector: the AutoML sweep.

Parity: reference ``core/.../stages/impl/selector/ModelSelector.scala:72-264``
— an Estimator of (label RealNN, features OPVector) -> Prediction that:
splits data (Splitter/Balancer/Cutter), runs the validator over every
(estimator, param-grid) candidate, refits the winner on the prepared
training data, evaluates train + holdout with every evaluator, and emits a
``ModelSelectorSummary``; the fitted stage is a ``SelectedModel`` wrapping
the winning PredictionModel.

TPU-first (SURVEY §2.7 P3): per fold, each candidate family trains its whole
hyperparameter grid as one stacked vmapped program (``grid_fit_arrays``);
folds iterate sequentially (their programs are identical, so compile once,
run k times). No thread pool, no executor dispatch.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu.evaluators.base import EvaluatorBase
from transmogrifai_tpu.models.base import PredictionModel, Predictor
from transmogrifai_tpu.selector.splitters import DataSplitter
from transmogrifai_tpu.selector.validator import OpCrossValidation
from transmogrifai_tpu.stages.base import Estimator
from transmogrifai_tpu.types import feature_types as ft

__all__ = ["ModelSelector", "SelectedModel", "ModelSelectorSummary",
           "ModelEvaluation"]


@dataclass
class ModelEvaluation:
    model_name: str
    model_uid: str
    model_type: str
    params: dict
    metric_values: dict


@dataclass
class ModelSelectorSummary:
    validation_type: str
    validation_metric: str
    best_model_uid: str
    best_model_name: str
    best_model_type: str
    best_params: dict
    validation_results: list[ModelEvaluation] = field(default_factory=list)
    train_evaluation: dict = field(default_factory=dict)
    holdout_evaluation: dict = field(default_factory=dict)
    data_prep_results: dict = field(default_factory=dict)
    wall_time_s: float = 0.0

    def to_json(self) -> dict:
        return {
            "validationType": self.validation_type,
            "validationMetric": self.validation_metric,
            "bestModelUID": self.best_model_uid,
            "bestModelName": self.best_model_name,
            "bestModelType": self.best_model_type,
            "bestModelParams": _jsonable(self.best_params),
            "validationResults": [
                {"modelName": r.model_name, "modelUID": r.model_uid,
                 "modelType": r.model_type, "modelParams": _jsonable(r.params),
                 "metricValues": _jsonable(r.metric_values)}
                for r in self.validation_results],
            "trainEvaluation": _jsonable(self.train_evaluation),
            "holdoutEvaluation": _jsonable(self.holdout_evaluation),
            "dataPrepResults": _jsonable(self.data_prep_results),
            "wallTimeSeconds": self.wall_time_s,
        }

    @staticmethod
    def from_json(d: dict) -> "ModelSelectorSummary":
        return ModelSelectorSummary(
            validation_type=d.get("validationType", ""),
            validation_metric=d.get("validationMetric", ""),
            best_model_uid=d.get("bestModelUID", ""),
            best_model_name=d.get("bestModelName", ""),
            best_model_type=d.get("bestModelType", ""),
            best_params=d.get("bestModelParams", {}),
            validation_results=[
                ModelEvaluation(
                    model_name=r.get("modelName", ""),
                    model_uid=r.get("modelUID", ""),
                    model_type=r.get("modelType", ""),
                    params=r.get("modelParams", {}),
                    metric_values=r.get("metricValues", {}))
                for r in d.get("validationResults", [])],
            train_evaluation=d.get("trainEvaluation", {}),
            holdout_evaluation=d.get("holdoutEvaluation", {}),
            data_prep_results=d.get("dataPrepResults", {}),
            wall_time_s=d.get("wallTimeSeconds", 0.0),
        )


def _jsonable(x: Any) -> Any:
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, (np.floating, np.integer)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    return x


class SelectedModel(PredictionModel):
    """The fitted winner; delegates to the wrapped PredictionModel."""

    def __init__(self, model: Optional[PredictionModel] = None,
                 summary: Optional[ModelSelectorSummary] = None,
                 uid: Optional[str] = None):
        self.model = model
        self.summary = summary
        super().__init__(uid=uid)

    def device_params(self):
        return self.model.device_params()

    def device_apply(self, params, col):
        return self.model.device_apply(params, col)

    def transform_row(self, *values):
        return self.model.transform_row(*values)

    def config(self):
        return {"model_class": type(self.model).__name__,
                "model_config": self.model.config(),
                "summary": self.summary.to_json() if self.summary else None}

    @classmethod
    def from_config(cls, config, uid=None):
        from transmogrifai_tpu.stages.base import STAGE_REGISTRY
        model_cls = STAGE_REGISTRY[config["model_class"]]
        model = model_cls.from_config(config.get("model_config") or {})
        summary = None
        if config.get("summary"):
            summary = ModelSelectorSummary.from_json(config["summary"])
        return cls(model=model, summary=summary, uid=uid)

    def fitted_state(self):
        return self.model.fitted_state()

    def set_fitted_state(self, state):
        self.model.set_fitted_state(state)


class ModelSelector(Estimator):
    in_types = (ft.RealNN, ft.OPVector)
    out_type = ft.Prediction

    def __init__(self,
                 models_and_grids: Sequence[tuple[Predictor, Sequence[dict]]],
                 validator: Optional[OpCrossValidation] = None,
                 splitter: Optional[DataSplitter] = None,
                 evaluators: Sequence[EvaluatorBase] = (),
                 validation_metric: Optional[str] = None,
                 uid: Optional[str] = None):
        if not models_and_grids:
            raise ValueError("ModelSelector needs at least one candidate model")
        self.models_and_grids = [(m, list(g) or [{}]) for m, g in models_and_grids]
        self.validator = validator or OpCrossValidation()
        self.splitter = splitter
        self.evaluators = list(evaluators)
        if not self.evaluators:
            raise ValueError("ModelSelector needs at least one evaluator")
        self.validation_metric = validation_metric or \
            self.evaluators[0].default_metric
        super().__init__(uid=uid)

    def fit_model(self, data) -> SelectedModel:
        from transmogrifai_tpu.dag import _plog
        t0 = time.time()
        label_name, feat_name = self.input_names
        X = data.device_col(feat_name).values
        y = data.device_col(label_name).values
        n = int(X.shape[0])
        ev0 = self.evaluators[0]
        bigger = ev0.larger_is_better(self.validation_metric)

        # -- split & prepare -------------------------------------------------
        prep_results: dict = {}
        if self.splitter is not None:
            # pull the label to host only when the splitter actually needs it
            y_np = np.asarray(y) if getattr(self.splitter, "requires_label",
                                            True) else None
            train_idx, holdout_idx = self.splitter.split_indices(n, y_np)
            train_idx, w_train = self.splitter.prepare_indices(
                train_idx, y_np)
            if self.splitter.summary:
                prep_results = {self.splitter.summary.splitter:
                                self.splitter.summary.detail}
        else:
            train_idx = np.arange(n)
            holdout_idx = np.zeros(0, dtype=np.int64)
            w_train = np.ones(n, dtype=np.float32)
        Xt, yt = X[jnp.asarray(train_idx)], y[jnp.asarray(train_idx)]
        wt = jnp.asarray(w_train)

        # -- validation sweep ------------------------------------------------
        results: list[ModelEvaluation] = []
        mean_metrics: list[tuple[float, int, int]] = []  # (metric, cand_i, grid_j)
        yt_np = (np.asarray(yt)
                 if getattr(self.validator, "stratify", False) else None)
        _folds = self.validator.splits(int(Xt.shape[0]), yt_np)
        per_candidate_scores: dict[tuple[int, int], list[float]] = {}
        _plog("selector: split+prepare", t0)
        batch_metrics = getattr(ev0, "metric_batch_scores", None)
        t1 = time.time()
        for tr, va in _folds:
            jtr, jva = jnp.asarray(tr), jnp.asarray(va)
            Xtr, ytr, wtr = Xt[jtr], yt[jtr], wt[jtr]
            Xva, yva = Xt[jva], yt[jva]
            for ci, (est, grid) in enumerate(self.models_and_grids):
                models = est.grid_fit_arrays(Xtr, ytr, wtr, grid)
                scores = (est.grid_predict_scores(models, Xva)
                          if batch_metrics is not None else None)
                if scores is not None:
                    # fast path: one device program scores + one computes the
                    # metric for the whole grid; a single host sync per
                    # (fold, family)
                    vals = batch_metrics(yva, scores, self.validation_metric)
                    for gj in range(len(models)):
                        per_candidate_scores.setdefault((ci, gj), []).append(
                            float(vals[gj]))
                    continue
                for gj, model in enumerate(models):
                    pred = model.predict_arrays(Xva)
                    metrics = ev0.evaluate_arrays(yva, pred)
                    val = ev0.metric_value(metrics, self.validation_metric)
                    per_candidate_scores.setdefault((ci, gj), []).append(val)
        for (ci, gj), vals in per_candidate_scores.items():
            est, grid = self.models_and_grids[ci]
            mean = float(np.mean(vals))
            mean_metrics.append((mean, ci, gj))
            results.append(ModelEvaluation(
                model_name=f"{type(est).__name__}_{ci}_{gj}",
                model_uid=est.uid,
                model_type=type(est).__name__,
                params={**est.params, **grid[gj]},
                metric_values={self.validation_metric: mean}))

        _plog("selector: CV sweep", t1)
        best_mean, best_ci, best_gj = (max if bigger else min)(
            mean_metrics, key=lambda t: t[0])
        best_est, best_grid = self.models_and_grids[best_ci]

        # -- refit winner on the full prepared training data -----------------
        t1 = time.time()
        best_params = {**best_est.params, **best_grid[best_gj]}
        best_model = best_est.fit_arrays(Xt, yt, wt, best_params)
        _plog("selector: refit", t1)
        t1 = time.time()

        # -- train/holdout evaluation with every evaluator -------------------
        train_eval: dict = {}
        holdout_eval: dict = {}
        pred_train = best_model.predict_arrays(Xt)
        for ev in self.evaluators:
            train_eval[ev.name] = EvaluatorBase.to_json(
                ev.evaluate_arrays(yt, pred_train))
        if holdout_idx.size:
            Xh = X[jnp.asarray(holdout_idx)]
            yh = y[jnp.asarray(holdout_idx)]
            pred_h = best_model.predict_arrays(Xh)
            for ev in self.evaluators:
                holdout_eval[ev.name] = EvaluatorBase.to_json(
                    ev.evaluate_arrays(yh, pred_h))

        _plog("selector: train/holdout evaluation", t1)
        summary = ModelSelectorSummary(
            validation_type=self.validator.name,
            validation_metric=self.validation_metric,
            best_model_uid=best_est.uid,
            best_model_name=f"{type(best_est).__name__}_{best_ci}_{best_gj}",
            best_model_type=type(best_est).__name__,
            best_params=best_params,
            validation_results=results,
            train_evaluation=train_eval,
            holdout_evaluation=holdout_eval,
            data_prep_results=prep_results,
            wall_time_s=time.time() - t0,
        )
        return SelectedModel(model=best_model, summary=summary)
